"""Live metrics: a pure-stdlib rolling-histogram registry rendering
Prometheus text exposition format (version 0.0.4).

The serving server's GET /metrics (serving/server.py) is backed by one
`MetricsRegistry`: counters and gauges for the fleet state scraped at
collection time (queue depth, page-pool occupancy, weight generation,
per-replica liveness), and `RollingHistogram`s fed LIVE from the
telemetry event stream (`Recorder.add_sink`) for request latency — so
the scrape path costs a lock and a render, never a device sync or a
log parse.

"Rolling" means two things at once, both Prometheus-legal:

* the `_bucket`/`_sum`/`_count` series are CUMULATIVE (the exposition
  contract — rate() and histogram_quantile() work unmodified);
* a bounded ring of recent observations backs the registry's own
  `<name>_p50`/`<name>_p99` gauges, the live quantiles the autoscaler
  and a human under pager duress read directly without a PromQL
  engine in the loop.

Everything here is thread-safe under one registry lock; `render()` is
the only reader and every writer is O(#buckets).
"""

from __future__ import annotations

import threading
from collections import deque

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# default latency buckets (seconds): sub-ms to 10s, the serving envelope
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)

DEFAULT_WINDOW = 512


def _fmt(v) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def _labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    """Monotonic counter with optional labels (one child per label
    set)."""

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._values: dict = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        self._values[key] = self._values.get(key, 0.0) + amount

    def render(self) -> list:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} counter"]
        for key, v in sorted(self._values.items()):
            lines.append(f"{self.name}{_labels(dict(key))} {_fmt(v)}")
        if not self._values:
            lines.append(f"{self.name} 0")
        return lines


class Gauge:
    """Point-in-time value with optional labels."""

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._values: dict = {}

    def set(self, value: float, **labels) -> None:
        self._values[tuple(sorted(labels.items()))] = float(value)

    def clear(self) -> None:
        self._values.clear()

    def render(self) -> list:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        for key, v in sorted(self._values.items()):
            lines.append(f"{self.name}{_labels(dict(key))} {_fmt(v)}")
        return lines


class RollingHistogram:
    """Cumulative Prometheus histogram + a bounded ring of recent
    observations for live p50/p99 gauges."""

    def __init__(self, name: str, help_text: str,
                 buckets=DEFAULT_BUCKETS, window: int = DEFAULT_WINDOW):
        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._sum = 0.0
        self._count = 0
        self._window: deque = deque(maxlen=window)

    def observe(self, value: float) -> None:
        v = float(value)
        self._sum += v
        self._count += 1
        self._window.append(v)
        for i, edge in enumerate(self.buckets):
            if v <= edge:
                self._counts[i] += 1
                return
        self._counts[-1] += 1

    def quantile(self, q: float) -> float:
        """Exact quantile over the rolling window (not the cumulative
        buckets) — the live signal the p50/p99 gauges expose."""
        if not self._window:
            return 0.0
        vals = sorted(self._window)
        k = min(len(vals) - 1,
                max(0, int(round(q / 100.0 * (len(vals) - 1)))))
        return vals[k]

    def render(self) -> list:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        cum = 0
        for i, edge in enumerate(self.buckets):
            cum += self._counts[i]
            lines.append(f'{self.name}_bucket{{le="{_fmt(edge)}"}} {cum}')
        cum += self._counts[-1]
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{self.name}_sum {_fmt(round(self._sum, 9))}")
        lines.append(f"{self.name}_count {self._count}")
        for q, suffix in ((50, "p50"), (99, "p99")):
            lines.append(f"# HELP {self.name}_{suffix} rolling window "
                         f"quantile of {self.name}")
            lines.append(f"# TYPE {self.name}_{suffix} gauge")
            lines.append(f"{self.name}_{suffix} "
                         f"{_fmt(round(self.quantile(q), 9))}")
        return lines


class MetricsRegistry:
    """Thread-safe metric set + scrape-time collectors. `render()` first
    runs every registered collector (the engine-state scrape: queue
    depth, pool occupancy, replica liveness) with NO lock held — a
    collector reaches into engine/batcher/pool locks, and calling it
    under `_lock` couples this lock to all of theirs (the G026/D002
    fan-out-under-lock shape) — then renders every metric in
    registration order under the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: list = []
        self._collectors: list = []

    def register(self, metric):
        with self._lock:
            self._metrics.append(metric)
        return metric

    def counter(self, name, help_text) -> Counter:
        return self.register(Counter(name, help_text))

    def gauge(self, name, help_text) -> Gauge:
        return self.register(Gauge(name, help_text))

    def histogram(self, name, help_text, buckets=DEFAULT_BUCKETS,
                  window: int = DEFAULT_WINDOW) -> RollingHistogram:
        return self.register(RollingHistogram(name, help_text, buckets,
                                              window))

    def add_collector(self, fn) -> None:
        """`fn()` runs at every scrape, before rendering — set gauges
        from live state there. A collector failure is contained (the
        scrape must answer under incident conditions)."""
        with self._lock:
            self._collectors.append(fn)

    def observe(self, metric: RollingHistogram, value: float) -> None:
        with self._lock:
            metric.observe(value)

    def inc(self, metric: Counter, amount: float = 1.0, **labels) -> None:
        with self._lock:
            metric.inc(amount, **labels)

    def render(self) -> str:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                pass
        with self._lock:
            lines = []
            for m in self._metrics:
                lines.extend(m.render())
        return "\n".join(lines) + "\n"


def parse_exposition(text: str) -> dict:
    """Exposition text -> {metric_or_series: float} — the round-trip
    half the tests (and any stdlib-only scraper) use. `# HELP`/`# TYPE`
    lines are skipped; label sets stay part of the series key."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name] = float(value.replace("+Inf", "inf"))
        except ValueError:
            continue
    return out
