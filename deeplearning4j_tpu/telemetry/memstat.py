"""Device-memory ledger + sampler — the `memory` event's producer.

The `memory` event kind has existed in the schema since the recorder
landed and nothing ever emitted it; this module is the missing producer,
built so the walk it costs can NEVER land on the hot path:

* `MemoryLedger` attributes live device bytes to subsystems — params,
  optimizer state, KV-cache pages, prefetch buffers — by walking the
  pytrees each subsystem REGISTERS (a zero-arg callable returning the
  current tree, so hot-swapped weights and respawned caches stay
  attributed without re-registration). Whatever the registered trees do
  not cover is the activation envelope: the residual between the
  live-array total and the attributed sum, i.e. XLA temp buffers,
  donated-intermediate slack, and anything in flight.
* `MemorySampler` snapshots `jax.live_arrays()` byte totals and the
  backend's `memory_stats()` (TPU HBM; CPU backends return None — the
  off-TPU fallback is live-array accounting only) and emits one ledger-
  annotated `memory` event. Sampling happens strictly at batch
  boundaries (the fit loops' `on_step`, the serving engine's stats
  tick) or on the sampler's own daemon thread — never inside a jitted
  region or a per-token loop (graftlint G029 enforces exactly that for
  everyone OUTSIDE this file).

Cadence control: the fit loops call `on_step(iteration)` every batch
and this module decides — env `DL4J_TPU_MEM_EVERY` (int, 0/unset =
off) names the step cadence, so the default fit loop pays one modulo
per batch and nothing else. The serving stats tick and the sampler
thread rate-limit through `maybe_sample` (min interval, monotonic
clock) so a tight scrape loop cannot turn the scrape path into a
live-array walk storm.

Concurrency: `_mu` guards only the rate-limit clock and the seen-peak
counter; the live-array walk and the event emit run OUTSIDE it (the
recorder takes its own lock — holding `_mu` across the emit would
couple the two, the D002 shape). The sampler thread is a daemon with
an Event-signalled stop, joinable, and never holds `_mu` while
sleeping.
"""

from __future__ import annotations

import os
import threading
import time

from deeplearning4j_tpu.telemetry.recorder import NullRecorder, Recorder

ENV_MEM_EVERY = "DL4J_TPU_MEM_EVERY"

# The closed subsystem vocabulary — the ledger map every `memory` event
# carries uses exactly these keys (plus "activations" for the residual
# and "other" when an explicit activation source is registered), so the
# /metrics ledger gauge and the tracetool mem report never meet an
# unknown label.
SUBSYSTEMS = ("params", "opt_state", "kv_pages", "prefetch",
              "activations", "other")


def tree_bytes(tree) -> int:
    """Total nbytes over a pytree's array leaves (host-side attribute
    reads only — no device sync)."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def live_array_totals() -> tuple[int, int]:
    """(total bytes, count) over every live jax array in the process —
    the off-TPU ground truth for HBM accounting."""
    import jax

    total = 0
    count = 0
    for arr in jax.live_arrays():
        total += int(getattr(arr, "nbytes", 0) or 0)
        count += 1
    return total, count


def device_memory_stats() -> dict:
    """Per-device backend memory_stats keyed by device id (the
    bytes_in_use / peak_bytes_in_use / bytes_limit triple). Empty on
    backends that expose none (CPU returns None)."""
    import jax

    devices = {}
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats:
            devices[str(dev.id)] = {
                k: stats[k] for k in ("bytes_in_use", "peak_bytes_in_use",
                                      "bytes_limit") if k in stats}
    return devices


class MemoryLedger:
    """Attributes live device bytes to subsystems by walking registered
    pytree sources at snapshot time."""

    def __init__(self):
        # subsystem -> list of zero-arg callables returning the CURRENT
        # pytree (so weight hot-swaps stay attributed); registration is
        # setup-time, snapshots are read-only over the list
        self._sources: dict[str, list] = {}
        self._mu = threading.Lock()

    def register(self, subsystem: str, source) -> "MemoryLedger":
        """Register a byte source under a subsystem name. `source` is a
        zero-arg callable returning a pytree (preferred — tracks
        replacement) or a pytree registered as-is."""
        if subsystem not in SUBSYSTEMS:
            raise ValueError(f"unknown ledger subsystem {subsystem!r}; "
                             f"one of {SUBSYSTEMS}")
        fn = source if callable(source) else (lambda t=source: t)
        with self._mu:
            self._sources.setdefault(subsystem, []).append(fn)
        return self

    def attributed(self) -> dict:
        """Per-subsystem byte totals over the registered sources (a
        failing source contributes 0 — attribution is best-effort and
        must never break the sampling path)."""
        with self._mu:
            sources = {k: list(v) for k, v in self._sources.items()}
        out = {}
        for subsystem, fns in sources.items():
            total = 0
            for fn in fns:
                try:
                    total += tree_bytes(fn())
                except Exception:
                    pass
            out[subsystem] = total
        return out

    def breakdown(self, live_total_bytes: int) -> dict:
        """The full ledger map for one snapshot: registered subsystems
        plus the residual. The residual is the activation envelope
        unless an explicit "activations" source is registered, in which
        case it lands under "other"."""
        out = self.attributed()
        residual = max(0, int(live_total_bytes) - sum(out.values()))
        key = "other" if "activations" in out else "activations"
        out[key] = out.get(key, 0) + residual
        return out


class MemorySampler:
    """Emits ledger-annotated `memory` events — at batch boundaries
    (`on_step`), on rate-limited ticks (`maybe_sample`), or on its own
    daemon thread (`start`/`stop`)."""

    def __init__(self, recorder: Recorder, ledger: MemoryLedger | None = None,
                 min_interval_s: float = 2.0,
                 mem_every: int | None = None):
        self.recorder = recorder
        self.ledger = ledger or MemoryLedger()
        self.min_interval_s = float(min_interval_s)
        if mem_every is None:
            try:
                mem_every = int(os.environ.get(ENV_MEM_EVERY, "0") or 0)
            except ValueError:
                mem_every = 0
        self.mem_every = max(0, int(mem_every))
        # guards the rate-limit clock + peak counter ONLY — never held
        # across the live-array walk or the recorder emit
        self._mu = threading.Lock()
        self._last_mono = float("-inf")
        self._last_event: dict = {}
        self._peak_live_bytes = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def enabled(self) -> bool:
        """False under a NullRecorder — the walk is skipped entirely,
        matching NullRecorder.memory()'s contract."""
        return not isinstance(self.recorder, NullRecorder)

    @property
    def peak_live_bytes(self) -> int:
        with self._mu:
            return self._peak_live_bytes

    @property
    def last(self) -> dict:
        """The most recent snapshot's payload (live bytes, devices,
        ledger) — the engines' stats()/metrics surface, so a scrape
        reads cached numbers instead of forcing a walk."""
        with self._mu:
            return dict(self._last_event)

    # ------------------------------------------------------------ sampling
    def sample(self, source: str, **fields) -> dict:
        """One snapshot now: live-array walk + backend stats + ledger
        breakdown, emitted as a single `memory` event."""
        if not self.enabled:
            return {}
        live_bytes, count = live_array_totals()
        devices = device_memory_stats()
        ledger = self.ledger.breakdown(live_bytes)
        payload = dict(live_array_bytes=int(live_bytes),
                       live_array_count=count, devices=devices,
                       ledger=ledger,
                       ledger_total_bytes=int(sum(ledger.values())),
                       source=source)
        with self._mu:
            if live_bytes > self._peak_live_bytes:
                self._peak_live_bytes = live_bytes
            self._last_mono = time.monotonic()
            self._last_event = dict(payload)
        return self.recorder.event("memory", **payload, **fields)

    def maybe_sample(self, source: str, **fields) -> dict:
        """Rate-limited snapshot: a no-op within `min_interval_s` of the
        previous one, so scrape/stats ticks can call it unconditionally."""
        if not self.enabled:
            return {}
        with self._mu:
            due = (time.monotonic() - self._last_mono
                   >= self.min_interval_s)
        if not due:
            return {}
        return self.sample(source, **fields)

    def on_step(self, iteration: int, **fields) -> dict:
        """The fit loops' batch-boundary hook: samples when the env
        cadence (`DL4J_TPU_MEM_EVERY`) divides the iteration; one modulo
        otherwise."""
        if self.mem_every <= 0 or not self.enabled:
            return {}
        if int(iteration) % self.mem_every != 0:
            return {}
        return self.sample("fit", iteration=int(iteration), **fields)

    # ------------------------------------------------------ sampler thread
    def start(self, interval_s: float = 10.0) -> "MemorySampler":
        """Background cadence for long-running processes with no
        convenient batch boundary (the serving control plane). Daemon
        thread; Event-signalled stop; one sample per interval."""
        if self._thread is not None or not self.enabled:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(float(interval_s)):
                try:
                    self.sample("sampler")
                except Exception:
                    pass  # sampling must never kill the host process

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="mem-sampler")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None


def sampler_for_net(net, recorder) -> MemorySampler:
    """The fit loops' cached per-net sampler: params + optimizer state
    registered on the ledger through late-bound callables (a restore or
    re-init swaps the trees; the callables track). Rebuilt only when
    the process recorder changed (a test installing its own)."""
    sampler = getattr(net, "_mem_sampler", None)
    if sampler is not None and sampler.recorder is recorder:
        return sampler
    ledger = MemoryLedger()
    ledger.register("params", lambda: getattr(net, "params", None))
    ledger.register("opt_state", lambda: getattr(net, "opt_state", None))
    sampler = MemorySampler(recorder, ledger)
    try:
        net._mem_sampler = sampler
    except Exception:
        pass  # slotted/frozen containers still get a working sampler
    return sampler
