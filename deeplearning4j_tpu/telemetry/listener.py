"""TelemetryListener — feeds the run recorder from fit() without host
syncs on the hot path.

`model.score_value` is a property whose getter converts the jitted
step's DEVICE scalar to a python float — a blocking host readback
(~100ms over a remote-device tunnel). A per-iteration listener that
reads it would serialize every step on the transfer (the G002 bug class
in listener form). This listener instead captures the RAW device scalar
(`model._score_raw`, no conversion) each iteration and materializes the
whole window in one batched fetch every `frequency` steps: one pipeline
stall per window instead of one per step. The scalars it fetches are
already `frequency` steps old by then — they are done computing, so the
stall is only the transfer latency of the newest one.
"""

from __future__ import annotations

import time

from deeplearning4j_tpu.optimize.listeners import IterationListener
from deeplearning4j_tpu.telemetry.recorder import Recorder, get_default


class TelemetryListener(IterationListener):
    """Emit a typed `step` event per iteration, buffered and flushed
    every `frequency` iterations (plus an optional `memory` snapshot per
    flush). Attach with `net.set_listeners(TelemetryListener())`; call
    `close()` (or rely on the final partial flush staying buffered at
    most `frequency-1` steps) after fit()."""

    def __init__(self, recorder: Recorder | None = None,
                 frequency: int = 50, snapshot_memory: bool = False):
        self.recorder = recorder
        self.frequency = max(1, frequency)
        self.snapshot_memory = snapshot_memory
        self._pending: list[tuple[int, object, float]] = []

    def _rec(self) -> Recorder:
        return self.recorder if self.recorder is not None else get_default()

    def iteration_done(self, model, iteration):
        # raw device scalar — NOT model.score_value (the float() there is
        # the per-step host sync this listener exists to avoid)
        raw = getattr(model, "_score_raw", None)
        self._pending.append((iteration, raw, time.perf_counter()))
        if len(self._pending) >= self.frequency:
            self.flush()

    def flush(self) -> None:
        """Materialize the buffered window: one batched host fetch, one
        `step` event per buffered iteration, throughput over the window."""
        if not self._pending:
            return
        rec = self._rec()
        window, self._pending = self._pending, []
        t_first, t_last = window[0][2], window[-1][2]
        its_per_sec = None
        if len(window) > 1 and t_last > t_first:
            its_per_sec = round((len(window) - 1) / (t_last - t_first), 4)
        for i, (iteration, raw, _t) in enumerate(window):
            score = None
            if raw is not None:
                try:
                    score = float(raw)
                except (TypeError, ValueError):
                    score = None
            fields = {}
            if i == len(window) - 1 and its_per_sec is not None:
                fields["iterations_per_sec"] = its_per_sec
            rec.step(iteration, score=score, **fields)
        if self.snapshot_memory:
            rec.memory(iteration=window[-1][0])

    def close(self) -> None:
        self.flush()
