"""Fleet-wide trace timeline: shard merge, span statistics, anomaly
detection, and Chrome-trace/Perfetto export over telemetry JSONL.

A production fleet writes N disjoint logs — the multi-process runtime
suffixes `DL4J_TPU_TELEMETRY` per process (`<path>.pN`,
recorder._process_scoped), serving replicas thread their events through
one shared file — and until this module nothing merged, correlated, or
watched them. This is the arXiv:1810.11112 characterization discipline
(know WHERE each step's time goes, across every process) applied to the
whole fleet:

* **merge** — `load_timeline` discovers `<path>.pN` shards (or takes
  the single file), tags every event with its `process` label, and
  orders the union causally: timestamp-major, then per-process `seq`
  (two events from ONE process never reorder, however close their
  clock stamps).
* **correlate** — spans carry `trace_id`/`span_id`/`parent_id`
  (recorder.py stamps them); `span_tree` rebuilds the per-trace tree
  (request → queue → batch_assemble → forward → compile), and `step`
  events join across processes by their shared `step-<n>` trace id.
* **analyze** — `span_stats` gives p50/p99/count per (process, span
  name); `detect_anomalies` emits typed findings:
    - `straggler`: cross-process step-completion skew past a threshold,
      or a process that STOPPED advancing while its peers continued
      (the `pN:hang@stepK` fault signature, from the JSONL alone);
    - `retrace`: a post-warmup `compile` span — a process that emitted
      warmup-flagged compiles and later compiles WITHOUT the flag broke
      the zero-retrace contract (the runtime witness of the bucket
      lattice's guarantee);
    - `input_wait_spike`: a pipelined input dequeue stalling past the
      threshold (the starve-proof contract's runtime witness);
    - `queue_spike`: a serving batch whose head request waited far past
      the batcher deadline, or an autoscale tick whose queue depth blew
      through the spike threshold;
    - `leak`: monotonic steady-state growth of a process's live device
      bytes across its `memory` snapshots (retained batches, an
      unbounded cache) past a growth floor;
    - `headroom`: a device's backend-reported `bytes_in_use` past the
      watermark fraction of `bytes_limit` (off-TPU runs carry no limit
      and never flag);
    - `cost_drift`: the placement cost model's predicted per-device
      memory vs a measured peak outside the documented factor — from
      typed `cost_drift` events (the costbook's reconcile loop) or the
      placement_search/memory join as a fallback.
* **export** — `to_perfetto` emits Chrome trace-event JSON
  (`ui.perfetto.dev` opens it directly): spans as complete ("X")
  slices, requests as slices over their `total_s`, instants ("i") for
  faults/steps/anomalies, counter ("C") tracks from `memory` events
  (live bytes + the per-subsystem ledger), one track per
  (process, replica).

Pure stdlib, no package-root imports — `tools/tracetool.py` runs this
under the same no-jax stubs as graftlint.
"""

from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass, field


# ---------------------------------------------------------------- loading

def discover_shards(path: str) -> list:
    """[(process_label, path), ...] for a telemetry path: the file
    itself when it exists (label "main"), plus/or every `<path>.pN`
    shard in process order. A sharded fleet run usually has ONLY the
    suffixed files; a bench sweep has the unsuffixed parent log AND the
    fleet modes' shards."""
    out = []
    if os.path.exists(path):
        out.append(("main", path))
    shards = []
    for cand in glob.glob(glob.escape(path) + ".p*"):
        m = re.match(r"\.p(\d+)$", cand[len(path):])
        if m:
            shards.append((int(m.group(1)), cand))
    out.extend((f"p{n}", p) for n, p in sorted(shards))
    if not out:
        raise FileNotFoundError(
            f"no telemetry at {path} (and no {path}.p* shards)")
    return out


def parse_events(text: str, process: str = "main") -> list:
    """JSONL text -> event dicts tagged with their `process` label.
    Non-JSON and truncated lines are skipped (the append-only contract
    means only the final line of a crashed writer can be partial)."""
    events = []
    for raw in text.splitlines():
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            ev = json.loads(raw)
        except json.JSONDecodeError:
            continue
        if not isinstance(ev, dict) or "event" not in ev:
            continue
        ev.setdefault("process", process)
        events.append(ev)
    return events


@dataclass
class Timeline:
    """The merged, causally-ordered fleet timeline."""

    events: list = field(default_factory=list)

    @property
    def processes(self) -> list:
        seen, out = set(), []
        for ev in self.events:
            p = ev.get("process", "main")
            if p not in seen:
                seen.add(p)
                out.append(p)
        return out

    def spans(self, name=None, process=None) -> list:
        return [ev for ev in self.events
                if ev.get("event") == "span"
                and (name is None or ev.get("name") == name)
                and (process is None or ev.get("process") == process)]

    def of_kind(self, kind: str) -> list:
        return [ev for ev in self.events if ev.get("event") == kind]


def merge_events(events: list) -> Timeline:
    """Causal order: timestamp-major; ties (and clock jitter inside one
    process) break on (process, seq) so a single process's stream never
    reorders."""
    ordered = sorted(
        events,
        key=lambda ev: (float(ev.get("ts", 0.0)), str(ev.get("process")),
                        int(ev.get("seq", 0))))
    return Timeline(events=ordered)


def timeline_from_events(events, process: str = "main") -> Timeline:
    """A Timeline from in-memory recorder events (`rec.events`) — the
    unit-test and single-process path; events lacking a `process` tag
    get the given label."""
    tagged = []
    for ev in events:
        ev = dict(ev)
        ev.setdefault("process", process)
        tagged.append(ev)
    return merge_events(tagged)


def load_timeline(path: str) -> Timeline:
    """Path (or its `.pN` shard family) -> merged Timeline."""
    events = []
    for label, shard in discover_shards(path):
        with open(shard) as fh:
            events.extend(parse_events(fh.read(), process=label))
    return merge_events(events)


# ------------------------------------------------------------- statistics

def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1,
            max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return float(sorted_vals[k])


def span_stats(timeline: Timeline) -> dict:
    """{(process, span name): {count, p50_ms, p99_ms, max_ms, total_s}}
    — where each process's time went, per span kind."""
    groups: dict = {}
    for ev in timeline.spans():
        if "seconds" not in ev:
            continue
        key = (ev.get("process", "main"), str(ev.get("name")))
        groups.setdefault(key, []).append(1000.0 * float(ev["seconds"]))
    out = {}
    for key, ms in groups.items():
        ms.sort()
        out[key] = {
            "count": len(ms),
            "p50_ms": round(_percentile(ms, 50), 3),
            "p99_ms": round(_percentile(ms, 99), 3),
            "max_ms": round(ms[-1], 3),
            "total_s": round(sum(ms) / 1000.0, 6),
        }
    return out


# ----------------------------------------------------------- span trees

def span_tree(timeline: Timeline, trace_id: str) -> list:
    """The span tree of one trace: roots (no parent, or parent outside
    the trace) with nested `children` lists. Events are grouped per
    process — `span_id`s are only unique within one — and non-span
    events that carry the trace (request, page_pool, error) attach as
    leaves under their parent span."""
    members = [ev for ev in timeline.events
               if ev.get("trace_id") == trace_id]
    nodes = {}
    for ev in members:
        sid = ev.get("span_id")
        key = (ev.get("process", "main"), sid)
        node = {"event": ev, "children": []}
        if sid is not None:
            nodes[key] = node
    roots = []
    for ev in members:
        sid = ev.get("span_id")
        node = (nodes[(ev.get("process", "main"), sid)]
                if sid is not None else {"event": ev, "children": []})
        parent = ev.get("parent_id")
        pkey = (ev.get("process", "main"), parent)
        if parent is not None and pkey in nodes \
                and nodes[pkey] is not node:
            nodes[pkey]["children"].append(node)
        else:
            roots.append(node)
    return roots


def trace_ids(timeline: Timeline) -> list:
    seen, out = set(), []
    for ev in timeline.events:
        tid = ev.get("trace_id")
        if tid is not None and tid not in seen:
            seen.add(tid)
            out.append(tid)
    return out


def render_tree(roots, indent: int = 0) -> str:
    """Human-readable tree (tracetool `tree`)."""
    lines = []
    for node in roots:
        ev = node["event"]
        name = (ev.get("name") if ev.get("event") == "span"
                else ev.get("event"))
        extra = ""
        if "seconds" in ev:
            extra = f" {1000.0 * float(ev['seconds']):.3f}ms"
        if ev.get("event") == "request":
            extra = f" id={ev.get('id')} total={ev.get('total_s')}s"
        lines.append("  " * indent
                     + f"{ev.get('process', 'main')}: {name}{extra}")
        lines.append(render_tree(node["children"], indent + 1))
    return "\n".join(l for l in lines if l)


# ------------------------------------------------------ anomaly detection

@dataclass(frozen=True)
class AnomalyConfig:
    """Detection thresholds. Defaults are deliberately generous — a
    contended CPU host must not read as a production incident; tighten
    per-deployment via tracetool flags."""

    straggler_skew_ms: float = 2000.0   # cross-process step-completion skew
    stall_factor: float = 3.0           # a process is stalled when silent
    #                                     for stall_factor x the fleet's
    #                                     median step gap (and past skew_ms)
    input_wait_spike_ms: float = 250.0  # pipelined dequeue stall
    input_wait_warmup: int = 2          # dequeues skipped per process
    #                                     (the first fills ride the
    #                                     producer's cold start)
    queue_spike_ms: float = 1000.0      # serving head-request wait
    queue_depth_spike: int = 64         # autoscale-tick queue depth
    # memory detectors (telemetry/memstat.py's `memory` events)
    leak_warmup: int = 2                # memory samples skipped per
    #                                     process (warmup allocations,
    #                                     compile-time temps)
    leak_min_samples: int = 4           # steady-state samples needed
    #                                     before monotonic growth reads
    #                                     as a leak
    leak_min_growth_bytes: float = 1 << 20  # total growth floor (1 MiB)
    headroom_watermark: float = 0.92    # live/limit past this is a
    #                                     headroom breach
    cost_drift_factor: float = 8.0      # predicted-vs-measured memory
    #                                     ratio band (see telemetry/
    #                                     costbook.DEFAULT_DRIFT_FACTOR)


def _step_completions(timeline: Timeline) -> dict:
    """{process: {iteration: ts}} over `step` events."""
    steps: dict = {}
    for ev in timeline.of_kind("step"):
        it = ev.get("iteration")
        if it is None:
            continue
        steps.setdefault(ev.get("process", "main"), {})[int(it)] = float(
            ev.get("ts", 0.0))
    return steps


def detect_stragglers(timeline: Timeline,
                      config: AnomalyConfig = AnomalyConfig()) -> list:
    """Cross-process step-completion skew + stalled-process (hang)
    findings. Only meaningful with >= 2 step-emitting processes."""
    steps = _step_completions(timeline)
    procs = [p for p, s in steps.items() if s]
    if len(procs) < 2:
        return []
    findings = []
    all_steps = sorted({s for per in steps.values() for s in per})
    # skew on steps every process completed
    for s in all_steps:
        done = {p: steps[p][s] for p in procs if s in steps[p]}
        if len(done) != len(procs):
            continue
        skew_ms = 1000.0 * (max(done.values()) - min(done.values()))
        if skew_ms > config.straggler_skew_ms:
            slowest = max(done, key=done.get)
            findings.append({
                "anomaly": "straggler", "mode": "skew", "step": s,
                "process": slowest,
                "skew_ms": round(skew_ms, 3),
                "threshold_ms": config.straggler_skew_ms})
    # stalled processes: stopped advancing while the fleet continued
    fleet_last = max(max(per) for per in steps.values())
    gaps = []
    for per in steps.values():
        ordered = sorted(per)
        gaps.extend(per[b] - per[a]
                    for a, b in zip(ordered, ordered[1:]))
    gaps.sort()
    median_gap_s = _percentile(gaps, 50) if gaps else 0.0
    for p in procs:
        last = max(steps[p])
        if last >= fleet_last:
            continue
        # a peer completed a LATER step — how long after this process
        # went silent?
        later = [ts for q in procs if q != p
                 for s, ts in steps[q].items() if s > last]
        if not later:
            continue
        silent_ms = 1000.0 * (max(later) - steps[p][last])
        if silent_ms > max(config.straggler_skew_ms,
                           1000.0 * config.stall_factor * median_gap_s):
            findings.append({
                "anomaly": "straggler", "mode": "stall",
                "process": p, "step": last + 1,
                "last_step": last,
                "fleet_step": fleet_last,
                "skew_ms": round(silent_ms, 3),
                "threshold_ms": config.straggler_skew_ms})
    return findings


def detect_retraces(timeline: Timeline) -> list:
    """Post-warmup retraces, per process: once a process has emitted a
    warmup-flagged `compile` span (the serving warmup discipline is in
    effect), any LATER `compile` without the flag is a shape that
    escaped the bucket lattice — the zero-retrace contract's runtime
    witness. Training runs never set the flag and never flag here
    (their first-dispatch compiles are the expected cost)."""
    warmed: set = set()
    findings = []
    for ev in timeline.events:
        if ev.get("event") != "span" or ev.get("name") != "compile":
            continue
        p = ev.get("process", "main")
        # one process file can hold many runs (the bench sweep's shared
        # log): warmup discipline is scoped per (process, run)
        scope = (p, ev.get("run"))
        if ev.get("warmup"):
            warmed.add(scope)
        elif scope in warmed:
            findings.append({
                "anomaly": "retrace", "process": p,
                "run": ev.get("run"),
                "bucket": ev.get("bucket"),
                "replica": ev.get("replica"),
                "seconds": ev.get("seconds"),
                "ts": ev.get("ts")})
    return findings


def detect_input_wait_spikes(timeline: Timeline,
                             config: AnomalyConfig = AnomalyConfig()
                             ) -> list:
    """Pipelined `input_wait` dequeues stalling past the threshold —
    the producer fell behind the step loop. The synchronous fallback's
    spans (pipelined=false) measure the whole conversion and are
    exempt; the first `input_wait_warmup` dequeues per process ride the
    producer's cold start and are skipped."""
    findings = []
    seen: dict = {}
    for ev in timeline.events:
        if ev.get("event") != "span" or ev.get("name") != "input_wait":
            continue
        if not ev.get("pipelined"):
            continue
        p = ev.get("process", "main")
        seen[p] = seen.get(p, 0) + 1
        if seen[p] <= config.input_wait_warmup:
            continue
        wait_ms = 1000.0 * float(ev.get("seconds", 0.0))
        if wait_ms > config.input_wait_spike_ms:
            findings.append({
                "anomaly": "input_wait_spike", "process": p,
                "wait_ms": round(wait_ms, 3),
                "threshold_ms": config.input_wait_spike_ms,
                "ts": ev.get("ts")})
    return findings


def detect_queue_spikes(timeline: Timeline,
                        config: AnomalyConfig = AnomalyConfig()) -> list:
    """Serving queue pathologies: a batch whose head request waited far
    past the batcher's deadline (`queue` span), or an autoscale tick
    that sampled a queue depth past the spike threshold."""
    findings = []
    for ev in timeline.events:
        if ev.get("event") == "span" and ev.get("name") == "queue":
            wait_ms = 1000.0 * float(ev.get("seconds", 0.0))
            if wait_ms > config.queue_spike_ms:
                findings.append({
                    "anomaly": "queue_spike", "kind": "wait",
                    "process": ev.get("process", "main"),
                    "wait_ms": round(wait_ms, 3),
                    "threshold_ms": config.queue_spike_ms,
                    "ts": ev.get("ts")})
        elif ev.get("event") == "autoscale":
            depth = int(ev.get("queue_depth", 0))
            if depth > config.queue_depth_spike:
                findings.append({
                    "anomaly": "queue_spike", "kind": "depth",
                    "process": ev.get("process", "main"),
                    "queue_depth": depth,
                    "threshold": config.queue_depth_spike,
                    "ts": ev.get("ts")})
    return findings


def _memory_samples(timeline: Timeline) -> dict:
    """{process: [memory event, ...]} in timeline order."""
    out: dict = {}
    for ev in timeline.of_kind("memory"):
        out.setdefault(ev.get("process", "main"), []).append(ev)
    return out


def detect_leaks(timeline: Timeline,
                 config: AnomalyConfig = AnomalyConfig()) -> list:
    """Monotonic steady-state live-bytes growth, per process: after the
    first `leak_warmup` samples (warmup allocations and compile temps
    ride those), `leak_min_samples`+ snapshots whose `live_array_bytes`
    never decreases AND grows by `leak_min_growth_bytes` total is a
    leak — something (retained batches, an unbounded cache) is pinning
    device memory every step. One finding per process."""
    findings = []
    for process, samples in _memory_samples(timeline).items():
        vals = [int(ev.get("live_array_bytes", 0) or 0)
                for ev in samples][config.leak_warmup:]
        if len(vals) < config.leak_min_samples:
            continue
        if any(b < a for a, b in zip(vals, vals[1:])):
            continue  # any release breaks the monotonic-growth signature
        growth = vals[-1] - vals[0]
        if growth < config.leak_min_growth_bytes:
            continue
        findings.append({
            "anomaly": "leak", "process": process,
            "samples": len(vals),
            "first_bytes": vals[0], "last_bytes": vals[-1],
            "growth_bytes": growth,
            "threshold_bytes": int(config.leak_min_growth_bytes),
            "ts": samples[-1].get("ts")})
    return findings


def detect_headroom(timeline: Timeline,
                    config: AnomalyConfig = AnomalyConfig()) -> list:
    """HBM headroom breaches: any device whose backend-reported
    `bytes_in_use / bytes_limit` passed the watermark (off-TPU runs
    carry no `bytes_limit` and never flag here — live-array accounting
    has no ceiling to breach). One finding per (process, device): the
    FIRST breach is the evidence; repeats add nothing."""
    findings = []
    seen: set = set()
    for ev in timeline.of_kind("memory"):
        process = ev.get("process", "main")
        for dev_id, stats in (ev.get("devices") or {}).items():
            limit = int(stats.get("bytes_limit", 0) or 0)
            in_use = int(stats.get("bytes_in_use", 0) or 0)
            if limit <= 0:
                continue
            ratio = in_use / limit
            if ratio <= config.headroom_watermark:
                continue
            key = (process, dev_id)
            if key in seen:
                continue
            seen.add(key)
            findings.append({
                "anomaly": "headroom", "process": process,
                "device": dev_id, "bytes_in_use": in_use,
                "bytes_limit": limit, "ratio": round(ratio, 4),
                "watermark": config.headroom_watermark,
                "ts": ev.get("ts")})
    return findings


def detect_cost_drift(timeline: Timeline,
                      config: AnomalyConfig = AnomalyConfig()) -> list:
    """Cost-model drift: the placement search's predicted per-device
    memory vs a measured peak, outside the documented factor band.

    Two evidence paths. Preferred: typed `cost_drift` events (the
    costbook's reconcile loop already computed predicted/measured/ratio
    — each event carries its own `factor`, falling back to the config's
    band). Fallback, for timelines where nothing reconciled live: join
    each (process, run)'s LAST `placement_search.winner_memory_bytes`
    against the max measured bytes from that same (process, run)'s
    later `memory` events."""
    findings = []
    reconciled: set = set()
    for ev in timeline.of_kind("cost_drift"):
        process = ev.get("process", "main")
        reconciled.add((process, ev.get("run")))
        ratio = float(ev.get("ratio", 0.0) or 0.0)
        factor = float(ev.get("factor", 0) or config.cost_drift_factor)
        if ratio <= 0 or factor <= 1:
            continue
        if 1.0 / factor <= ratio <= factor:
            continue
        findings.append({
            "anomaly": "cost_drift", "process": process,
            "predicted_bytes": ev.get("predicted_bytes"),
            "measured_bytes": ev.get("measured_bytes"),
            "ratio": round(ratio, 4), "factor": factor,
            "source": ev.get("source", "event"),
            "ts": ev.get("ts")})
    # fallback join, scoped per (process, run) — a shared bench log
    # holds many modes' runs and a search in one must never reconcile
    # against another's memory samples
    searches: dict = {}
    for ev in timeline.of_kind("placement_search"):
        predicted = int(ev.get("winner_memory_bytes", 0) or 0)
        if predicted > 0:
            searches[(ev.get("process", "main"), ev.get("run"))] = ev
    for scope, search in searches.items():
        if scope in reconciled:
            continue
        process, run = scope
        measured = 0
        last_ts = None
        for ev in timeline.of_kind("memory"):
            if (ev.get("process", "main"), ev.get("run")) != scope:
                continue
            if float(ev.get("ts", 0.0)) < float(search.get("ts", 0.0)):
                continue
            per_dev = [int(s.get("peak_bytes_in_use", 0) or 0)
                       for s in (ev.get("devices") or {}).values()]
            cand = max(per_dev) if any(per_dev) \
                else int(ev.get("live_array_bytes", 0) or 0)
            if cand > measured:
                measured = cand
                last_ts = ev.get("ts")
        if measured <= 0:
            continue
        predicted = int(search.get("winner_memory_bytes", 0) or 0)
        ratio = measured / predicted
        factor = config.cost_drift_factor
        if 1.0 / factor <= ratio <= factor:
            continue
        findings.append({
            "anomaly": "cost_drift", "process": process, "run": run,
            "predicted_bytes": predicted, "measured_bytes": measured,
            "ratio": round(ratio, 4), "factor": factor,
            "source": "join", "ts": last_ts})
    return findings


def detect_anomalies(timeline: Timeline,
                     config: AnomalyConfig = AnomalyConfig()) -> list:
    """All detectors, in timeline order of evidence. Each finding is a
    typed dict whose `anomaly` field names the kind — the same payload
    `Recorder.anomaly` puts on a live record."""
    return (detect_stragglers(timeline, config)
            + detect_retraces(timeline)
            + detect_input_wait_spikes(timeline, config)
            + detect_queue_spikes(timeline, config)
            + detect_leaks(timeline, config)
            + detect_headroom(timeline, config)
            + detect_cost_drift(timeline, config))


# -------------------------------------------------------- live watching

class StragglerWatch:
    """Incremental straggler detection for a LIVE fleet — the elastic
    supervisor's heartbeat-path consumer. Each `poll()` re-reads the
    fleet's telemetry shards (small, append-only files), runs
    `detect_stragglers`, and emits each NEW finding exactly once as a
    typed `anomaly` event through the recorder — so a skewing or hung
    worker is in the supervisor's journal while the generation is still
    running, not just after the launcher reaps it."""

    def __init__(self, path: str, recorder=None,
                 config: AnomalyConfig = AnomalyConfig(),
                 min_interval_s: float = 1.0, clock=None):
        import time as _time

        self.path = path
        self.config = config
        self.min_interval_s = min_interval_s
        self._clock = clock or _time.monotonic
        self._last_poll = float("-inf")
        self._seen: set = set()
        self.findings: list = []
        if recorder is None:
            from deeplearning4j_tpu.telemetry.recorder import get_default
            recorder = get_default()
        self.recorder = recorder

    def poll(self, force: bool = False) -> list:
        now = self._clock()
        if not force and now - self._last_poll < self.min_interval_s:
            return []
        self._last_poll = now
        try:
            timeline = load_timeline(self.path)
        except (FileNotFoundError, OSError):
            return []  # no shards yet: the fleet has not started writing
        fresh = []
        for f in detect_stragglers(timeline, self.config):
            key = (f.get("mode"), f.get("process"), f.get("step"))
            if key in self._seen:
                continue
            self._seen.add(key)
            self.findings.append(f)
            fresh.append(f)
            payload = {k: v for k, v in f.items() if k != "anomaly"}
            self.recorder.anomaly(f["anomaly"], **payload)
        return fresh


class MemoryWatch:
    """Incremental memory-anomaly detection for a LIVE fleet — the
    elastic supervisor and fleet autoscaler consume this exactly the
    way they consume `StragglerWatch`: each `poll()` re-reads the
    telemetry shards, runs the leak / headroom / cost-drift detectors,
    and emits each NEW finding exactly once as a typed `anomaly` event
    — so a leaking or HBM-starved worker is in the journal while the
    run is still alive."""

    def __init__(self, path: str, recorder=None,
                 config: AnomalyConfig = AnomalyConfig(),
                 min_interval_s: float = 1.0, clock=None):
        import time as _time

        self.path = path
        self.config = config
        self.min_interval_s = min_interval_s
        self._clock = clock or _time.monotonic
        self._last_poll = float("-inf")
        self._seen: set = set()
        self.findings: list = []
        if recorder is None:
            from deeplearning4j_tpu.telemetry.recorder import get_default
            recorder = get_default()
        self.recorder = recorder

    @staticmethod
    def _key(f: dict) -> tuple:
        kind = f.get("anomaly")
        if kind == "headroom":
            return (kind, f.get("process"), f.get("device"))
        if kind == "cost_drift":
            return (kind, f.get("process"), f.get("run"),
                    f.get("predicted_bytes"))
        return (kind, f.get("process"))  # leak: one per process

    def poll(self, force: bool = False) -> list:
        now = self._clock()
        if not force and now - self._last_poll < self.min_interval_s:
            return []
        self._last_poll = now
        try:
            timeline = load_timeline(self.path)
        except (FileNotFoundError, OSError):
            return []
        found = (detect_leaks(timeline, self.config)
                 + detect_headroom(timeline, self.config)
                 + detect_cost_drift(timeline, self.config))
        fresh = []
        for f in found:
            key = self._key(f)
            if key in self._seen:
                continue
            self._seen.add(key)
            self.findings.append(f)
            fresh.append(f)
            payload = {k: v for k, v in f.items() if k != "anomaly"}
            self.recorder.anomaly(f["anomaly"], **payload)
        return fresh


# ---------------------------------------------------------------- export

def to_perfetto(timeline: Timeline) -> dict:
    """Chrome trace-event JSON (the Perfetto UI's legacy-but-universal
    format): spans and requests become complete ("X") slices placed at
    their START time (`ts - seconds`), everything else an instant
    ("i"). One pid per process, one tid per replica (0 when absent),
    process_name metadata rows so the Perfetto tracks are labelled."""
    events = []
    pid_of = {p: i for i, p in enumerate(timeline.processes)}
    if not pid_of:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    def start_of(ev) -> float:
        # spans and requests stamp COMPLETION time; their slice begins
        # `seconds`/`total_s` earlier — the base must cover the earliest
        # start or the first slice would sit at a negative timestamp
        ts = float(ev.get("ts", 0.0))
        if ev.get("event") == "span" and "seconds" in ev:
            return ts - float(ev["seconds"])
        if ev.get("event") == "request" and "total_s" in ev:
            return ts - float(ev["total_s"])
        return ts

    base = min((start_of(ev) for ev in timeline.events), default=0.0)

    def us(ts: float) -> float:
        return round(1e6 * (ts - base), 1)

    for p, pid in pid_of.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": f"telemetry:{p}"}})
    for ev in timeline.events:
        pid = pid_of[ev.get("process", "main")]
        tid = int(ev.get("replica", 0) or 0)
        ts = float(ev.get("ts", 0.0))
        args = {k: v for k, v in ev.items()
                if k not in ("event", "ts", "process")
                and isinstance(v, (str, int, float, bool))}
        kind = ev.get("event")
        if kind == "span" and "seconds" in ev:
            dur = max(0.0, 1e6 * float(ev["seconds"]))
            events.append({"name": str(ev.get("name", "span")), "ph": "X",
                           "pid": pid, "tid": tid,
                           "ts": us(ts - float(ev["seconds"])),
                           "dur": round(dur, 1), "args": args})
        elif kind == "request" and "total_s" in ev:
            dur = max(0.0, 1e6 * float(ev["total_s"]))
            events.append({"name": f"request:{ev.get('id', '?')}",
                           "ph": "X", "pid": pid, "tid": tid,
                           "ts": us(ts - float(ev["total_s"])),
                           "dur": round(dur, 1), "args": args})
        elif kind == "memory":
            # counter tracks: live bytes + the ledger breakdown render
            # as stacked area series in the Perfetto UI
            series = {"live_array_bytes":
                      int(ev.get("live_array_bytes", 0) or 0)}
            for subsystem, nbytes in (ev.get("ledger") or {}).items():
                series[f"ledger_{subsystem}"] = int(nbytes or 0)
            events.append({"name": "device_memory", "ph": "C",
                           "pid": pid, "tid": 0, "ts": us(ts),
                           "args": series})
        else:
            events.append({"name": str(kind), "ph": "i", "pid": pid,
                           "tid": tid, "ts": us(ts), "s": "p",
                           "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# --------------------------------------------------------- memory report

def memory_report(timeline: Timeline) -> dict:
    """The `tracetool mem` report: per-process memory timeline summary
    (sample count, first/last/peak live bytes, the last ledger
    breakdown, device limits when the backend reported them) plus the
    compiled-cost book (per-entry flops / bytes accessed / peak temp
    from `cost` events) and every `cost_drift` reconciliation."""
    processes = {}
    for process, samples in sorted(_memory_samples(timeline).items()):
        vals = [int(ev.get("live_array_bytes", 0) or 0) for ev in samples]
        last = samples[-1]
        limits = {}
        for ev in samples:
            for dev_id, stats in (ev.get("devices") or {}).items():
                if stats.get("bytes_limit"):
                    limits[dev_id] = int(stats["bytes_limit"])
        processes[process] = {
            "samples": len(samples),
            "first_bytes": vals[0], "last_bytes": vals[-1],
            "peak_bytes": max(vals),
            "growth_bytes": vals[-1] - vals[0],
            "ledger": dict(last.get("ledger") or {}),
            "sources": sorted({str(ev.get("source", "?"))
                               for ev in samples}),
            "device_limits": limits,
        }
    book = {}
    for ev in timeline.of_kind("cost"):
        key = f"{ev.get('entry', '?')}::{ev.get('shape')}"
        book[key] = {k: ev[k] for k in
                     ("flops", "bytes_accessed", "peak_temp_bytes",
                      "argument_bytes", "output_bytes") if k in ev}
    drifts = [{k: ev.get(k) for k in
               ("process", "predicted_bytes", "measured_bytes",
                "ratio", "factor", "source")}
              for ev in timeline.of_kind("cost_drift")]
    return {"processes": processes, "cost_book": book,
            "cost_drift": drifts}


# ------------------------------------------------------- TRACE artifacts

def metric_lines(timeline: Timeline, anomalies: list,
                 prefix: str = "trace") -> list:
    """Benchdiff-diffable TRACE rows: per-(process, span) p50/p99 as
    lower-is-better latency rows, plus `anomaly_count` and
    `straggler_skew_ms` which regress on ANY increase (tools/
    benchdiff.py — an anomaly appearing is never an improvement)."""
    lines = []
    stats = span_stats(timeline)
    for (process, name), row in sorted(stats.items()):
        for q in ("p50", "p99"):
            lines.append({
                "metric": f"{prefix}_span_{q}_ms::{process}::{name}",
                "value": row[f"{q}_ms"], "unit": "ms",
                "lower_is_better": True, "count": row["count"]})
    skews = [f.get("skew_ms", 0.0) for f in anomalies
             if f.get("anomaly") == "straggler"]
    by_kind: dict = {}
    for f in anomalies:
        by_kind[f["anomaly"]] = by_kind.get(f["anomaly"], 0) + 1
    lines.append({"metric": f"{prefix}_anomaly_count",
                  "value": len(anomalies), "unit": "count",
                  "lower_is_better": True, **{f"n_{k}": v
                                              for k, v in by_kind.items()}})
    lines.append({"metric": f"{prefix}_straggler_skew_ms",
                  "value": round(max(skews), 3) if skews else 0.0,
                  "unit": "ms", "lower_is_better": True})
    # memory rows: leak_count / cost_drift_ratio regress on ANY increase
    # (the retrace rise-from-zero rule — a leak appearing is never an
    # improvement); hbm_peak_bytes rides only when samples exist, so
    # memory-less timelines keep their row set unchanged
    lines.append({"metric": f"{prefix}_leak_count",
                  "value": sum(1 for f in anomalies
                               if f.get("anomaly") == "leak"),
                  "unit": "count", "lower_is_better": True})
    drift_ratios = [max(float(f.get("ratio", 0.0) or 0.0),
                        (1.0 / float(f["ratio"]))
                        if float(f.get("ratio", 0.0) or 0.0) > 0 else 0.0)
                    for f in anomalies
                    if f.get("anomaly") == "cost_drift"]
    lines.append({"metric": f"{prefix}_cost_drift_ratio",
                  "value": round(max(drift_ratios), 4) if drift_ratios
                  else 0.0,
                  "unit": "ratio", "lower_is_better": True})
    mem = [int(ev.get("live_array_bytes", 0) or 0)
           for ev in timeline.of_kind("memory")]
    if mem:
        lines.append({"metric": f"{prefix}_hbm_peak_bytes",
                      "value": max(mem), "unit": "bytes",
                      "lower_is_better": True, "samples": len(mem)})
    return lines
