"""Compiled-cost book — per-executable XLA cost/memory analyses as
typed `cost` events, harvested at warmup/compile time.

Every jitted entry the repo warms (serving forward buckets, prefill
chunks, the decode/verify step, the fused fit scan) already runs under
a `compile` span. This module rides that moment: AFTER the warm call
has populated the jit cache, `jitted.lower(*args)` is a jaxpr-cache
hit — it does NOT re-trace, so the trace counters the zero-retrace
gates freeze stay frozen. Flops and bytes-accessed come straight off
the lowered program (`Lowered.cost_analysis()`, no backend compile);
`memory_analysis()` (peak temp, argument/output/code bytes) needs the
AOT executable, so `.compile()` runs once per UNIQUE lowered program
per process (fingerprint cache — re-warmed replicas and respawns hit
it), paid entirely at warmup; ZERO hot-path cost, by construction.

The book is the denominator store for MFU: measured step wall-clock
over the recorded flops against the device's peak gives
`mfu_live`, the gauge /metrics and the bench summary expose. It is
also the measured side of the placement cost model's calibration loop:
`reconcile()` emits a typed `cost_drift` event naming the search's
predicted per-device bytes, the measured peak, and their ratio —
outside the documented factor is a detector anomaly
(telemetry/trace.py `detect_cost_drift`).

The documented drift factor: `DEFAULT_DRIFT_FACTOR = 8.0`. The search
predicts packed parameter-resident bytes per device from exact
rational arithmetic; a live process measures float32 live arrays plus
optimizer state plus runtime slack (and, off-TPU, live-array
accounting stands in for HBM). Within 8x in either direction is
calibration-pass; outside it the model has rotted and the
`cost_drift` anomaly fires.

Everything here is best-effort: an AOT API that a backend does not
implement degrades to a partial (or absent) book entry, never an
exception on the warmup path.
"""

from __future__ import annotations

import threading

from deeplearning4j_tpu.telemetry.recorder import NullRecorder, Recorder

DEFAULT_DRIFT_FACTOR = 8.0

# Peak dense bf16 FLOP/s per device kind — the MFU denominator. The
# fallback (1e12) keeps off-TPU MFU informational (a tiny number),
# never a crash.
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}
DEFAULT_PEAK_FLOPS = 1e12


def peak_flops(device_kind: str | None) -> float:
    """Peak FLOP/s for a device kind string (substring match so
    platform-version suffixes don't miss)."""
    kind = device_kind or ""
    for name, peak in PEAK_BF16_FLOPS.items():
        if name in kind:
            return peak
    return DEFAULT_PEAK_FLOPS


def _first(analysis):
    """cost_analysis() returns a dict on some jax versions, a
    per-partition list of dicts on others — normalize to one dict."""
    if isinstance(analysis, (list, tuple)):
        return analysis[0] if analysis else {}
    return analysis or {}


# Fingerprint -> compile-derived field dict. memory_analysis() needs
# the AOT executable, and an explicit .compile() does NOT share the
# warm call's executable cache — it is one real XLA compile. Keying the
# result on the lowered module's text hash makes each unique program
# pay that compile once per process: re-warmed replicas, engine
# respawns, and identical configs all hit the cache (params ride as
# jit ARGUMENTS, so weights never land in the fingerprinted HLO).
_HARVEST_CACHE: dict = {}
_HARVEST_MU = threading.Lock()


def harvest(jitted, *args, **kwargs) -> dict:
    """Lower an ALREADY-WARMED jit wrapper and pull XLA's own analyses.
    Returns a (possibly partial) field dict; {} when the backend
    exposes nothing. Call this ONLY at warmup/compile time — graftlint
    G029 flags memory_analysis() anywhere near a hot loop outside
    telemetry/."""
    fields: dict = {}
    try:
        lowered = jitted.lower(*args, **kwargs)
    except Exception:
        return fields
    # flops / bytes accessed straight off the lowered (pre-optimization)
    # program where the jax version exposes it — no backend compile
    try:
        ca = _first(lowered.cost_analysis())
        if "flops" in ca:
            fields["flops"] = float(ca["flops"])
        if "bytes accessed" in ca:
            fields["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:
        pass
    fp = None
    try:
        import hashlib

        fp = hashlib.sha1(lowered.as_text().encode()).hexdigest()
        with _HARVEST_MU:
            cached = _HARVEST_CACHE.get(fp)
        if cached is not None:
            return {**cached, **fields}
    except Exception:
        pass
    try:
        compiled = lowered.compile()
    except Exception:
        return fields
    compiled_fields: dict = {}
    if "flops" not in fields or "bytes_accessed" not in fields:
        try:
            ca = _first(compiled.cost_analysis())
            if "flops" in ca:
                compiled_fields["flops"] = float(ca["flops"])
            if "bytes accessed" in ca:
                compiled_fields["bytes_accessed"] = float(
                    ca["bytes accessed"])
        except Exception:
            pass
    try:
        ma = compiled.memory_analysis()
        for attr, key in (("temp_size_in_bytes", "peak_temp_bytes"),
                          ("argument_size_in_bytes", "argument_bytes"),
                          ("output_size_in_bytes", "output_bytes"),
                          ("generated_code_size_in_bytes",
                           "generated_code_bytes")):
            val = getattr(ma, attr, None)
            if val is not None:
                compiled_fields[key] = int(val)
    except Exception:
        pass
    if fp is not None and compiled_fields:
        with _HARVEST_MU:
            _HARVEST_CACHE.setdefault(fp, dict(compiled_fields))
    return {**compiled_fields, **fields}


class CostBook:
    """Per-(entry, shape) compiled-cost records + the typed `cost`
    event emitter. One book per engine/net; `record()` dedups, so
    respawn re-warms (which compile nothing) also emit nothing."""

    def __init__(self, recorder: Recorder):
        self.recorder = recorder
        # (entry, shape key) -> harvested field dict; `_mu` guards the
        # dict only — the lower/compile harvest and the emit run outside
        self._book: dict = {}
        self._mu = threading.Lock()

    @property
    def enabled(self) -> bool:
        return not isinstance(self.recorder, NullRecorder)

    @staticmethod
    def _key(entry: str, shape) -> tuple:
        try:
            frozen = tuple(shape) if isinstance(shape, (list, tuple)) \
                else (shape,)
        except Exception:
            frozen = (repr(shape),)
        return (entry, frozen)

    def record(self, entry: str, shape, jitted, args,
               kwargs=None, **extra) -> dict:
        """Harvest one warmed executable into the book and emit its
        `cost` event. `shape` is the warmed shape key (a bucket key
        list, a [B, T] pair, ...). Returns the event dict ({} when
        disabled, already recorded, or nothing harvestable)."""
        if not self.enabled:
            return {}
        key = self._key(entry, shape)
        with self._mu:
            if key in self._book:
                return {}
        fields = harvest(jitted, *args, **(kwargs or {}))
        if not fields:
            return {}
        with self._mu:
            if key in self._book:  # lost a warmup race: keep the first
                return {}
            self._book[key] = dict(fields)
        return self.recorder.cost(entry, list(key[1]), **fields, **extra)

    # ------------------------------------------------------------- lookups
    def entries(self) -> dict:
        with self._mu:
            return {k: dict(v) for k, v in self._book.items()}

    def flops(self, entry: str | None = None, shape=None) -> float:
        """Recorded flops: for one (entry, shape), for every shape of
        one entry, or the whole book."""
        with self._mu:
            items = list(self._book.items())
        total = 0.0
        for (name, frozen), fields in items:
            if entry is not None and name != entry:
                continue
            if shape is not None and frozen != self._key(entry or name,
                                                         shape)[1]:
                continue
            total += float(fields.get("flops", 0.0) or 0.0)
        return total

    def peak_temp_bytes(self) -> int:
        """Max XLA peak-temp over the book — the compiled side of the
        memory headline."""
        with self._mu:
            vals = [int(f.get("peak_temp_bytes", 0) or 0)
                    for f in self._book.values()]
        return max(vals) if vals else 0

    @staticmethod
    def mfu(flops: float, seconds: float, peak: float) -> float:
        """Model FLOPs utilization for one executed step: achieved
        FLOP/s over the device peak, clamped to [0, 1]."""
        if seconds <= 0 or peak <= 0 or flops <= 0:
            return 0.0
        return max(0.0, min(1.0, (flops / seconds) / peak))


def measured_peak_bytes() -> int:
    """The measured side of the calibration loop: the max per-device
    `peak_bytes_in_use` the backend reports, else (off-TPU) the current
    live-array byte total."""
    from deeplearning4j_tpu.telemetry.memstat import (device_memory_stats,
                                                      live_array_totals)

    devices = device_memory_stats()
    peaks = [int(d.get("peak_bytes_in_use", 0) or 0)
             for d in devices.values()]
    peak = max(peaks) if peaks else 0
    if peak > 0:
        return peak
    total, _ = live_array_totals()
    return total


def reconcile(recorder: Recorder, predicted_bytes: int, *,
              measured_bytes: int | None = None,
              factor: float = DEFAULT_DRIFT_FACTOR,
              source: str = "placement", **fields) -> dict:
    """Close the cost-model loop: predicted per-device bytes (the
    placement search's `winner_memory_bytes`) vs a measured peak, as a
    typed `cost_drift` event. Run this AFTER the first real step so the
    measurement covers a steady-state footprint. Returns the event; {}
    under a NullRecorder or a non-positive prediction (nothing to
    reconcile)."""
    if isinstance(recorder, NullRecorder):
        return {}
    predicted = int(predicted_bytes or 0)
    if predicted <= 0:
        return {}
    if measured_bytes is None:
        measured_bytes = measured_peak_bytes()
    return recorder.cost_drift(predicted_bytes=predicted,
                               measured_bytes=int(measured_bytes),
                               factor=float(factor), source=source,
                               **fields)
