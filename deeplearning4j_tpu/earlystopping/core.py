"""Early stopping — train-until-no-improvement protocol.

Reference: earlystopping/ — EarlyStoppingConfiguration (builder:
saver/termination/scoreCalculator/evalInterval),
trainer/BaseEarlyStoppingTrainer.java:82 (epoch loop: fit → score → check
terminations → save best), saver/{InMemoryModelSaver,LocalFileModelSaver},
scorecalc/DataSetLossCalculator, termination/* (MaxEpochs, MaxTime,
MaxScore, ScoreImprovement, BestScoreEpoch, InvalidScore).
"""

from __future__ import annotations

import copy
import math
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Optional


# ------------------------------------------------------------ score calcs
class ScoreCalculator:
    def calculate_score(self, net) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average network loss over a held-out iterator (reference
    scorecalc/DataSetLossCalculator.java — also covers the CG variant)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, net) -> float:
        total, count = 0.0, 0
        self.iterator.reset()
        while self.iterator.has_next():
            ds = self.iterator.next()
            total += net.score(ds) * ds.num_examples()
            count += ds.num_examples()
        if count == 0:
            return float("nan")
        return total / count if self.average else total


# ---------------------------------------------------------- terminations
class EpochTerminationCondition:
    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no score improvement (reference
    ScoreImprovementEpochTerminationCondition)."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self.best = math.inf
        self.since = 0

    def terminate(self, epoch, score):
        if score < self.best - self.min_improvement:
            self.best = score
            self.since = 0
        else:
            self.since += 1
        return self.since > self.patience


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    def __init__(self, best_expected_score: float):
        self.target = best_expected_score

    def terminate(self, epoch, score):
        return score < self.target


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self.start = time.monotonic()

    def terminate(self, last_score):
        return (time.monotonic() - self.start) > self.max_seconds


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, last_score):
        return last_score > self.max_score


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    def terminate(self, last_score):
        return math.isnan(last_score) or math.isinf(last_score)


# ----------------------------------------------------------------- savers
class ModelSaver:
    def save_best_model(self, net, score):
        raise NotImplementedError

    def save_latest_model(self, net, score):
        raise NotImplementedError

    def get_best_model(self):
        raise NotImplementedError


class InMemoryModelSaver(ModelSaver):
    """Keeps a deep copy of params/state in memory (reference InMemoryModelSaver)."""

    def __init__(self):
        self.best = None
        self.latest = None

    @staticmethod
    def _snapshot(net):
        import jax
        import jax.numpy as jnp

        snap = copy.copy(net)
        snap.params = jax.tree.map(jnp.copy, net.params)
        snap.state = jax.tree.map(jnp.copy, net.state)
        return snap

    def save_best_model(self, net, score):
        self.best = self._snapshot(net)

    def save_latest_model(self, net, score):
        self.latest = self._snapshot(net)

    def get_best_model(self):
        return self.best


class LocalFileModelSaver(ModelSaver):
    """Writes bestModel.zip / latestModel.zip (reference LocalFileModelSaver;
    covers the CG LocalFileGraphSaver too — one serializer handles both)."""

    def __init__(self, directory: Optional[str] = None):
        self.directory = directory or tempfile.mkdtemp(prefix="dl4j_tpu_es_")
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.directory, name)

    def save_best_model(self, net, score):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        ModelSerializer.write_model(net, self._path("bestModel.zip"))

    def save_latest_model(self, net, score):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        ModelSerializer.write_model(net, self._path("latestModel.zip"))

    def get_best_model(self):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        return ModelSerializer.restore(self._path("bestModel.zip"))


# ------------------------------------------------------------ config/result
@dataclass
class EarlyStoppingConfiguration:
    score_calculator: ScoreCalculator = None
    model_saver: ModelSaver = field(default_factory=InMemoryModelSaver)
    epoch_terminations: list = field(default_factory=list)
    iteration_terminations: list = field(default_factory=list)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclass
class EarlyStoppingResult:
    termination_reason: str
    termination_details: str
    score_vs_epoch: dict
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: object


class EarlyStoppingTrainer:
    """Epoch loop (reference trainer/BaseEarlyStoppingTrainer.java:82)."""

    def __init__(self, config: EarlyStoppingConfiguration, net, train_iterator):
        self.config = config
        self.net = net
        self.it = train_iterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        best_score, best_epoch = math.inf, -1
        scores = {}
        epoch = 0
        reason, details = "MaxEpochs", ""
        while True:
            self.it.reset()
            self.net.fit(self.it, epochs=1)
            # iteration-level terminations checked on the epoch's last score
            stop_iter = None
            for t in cfg.iteration_terminations:
                if t.terminate(self.net.score_value):
                    stop_iter = t
                    break
            if stop_iter is not None:
                reason = "IterationTermination"
                details = type(stop_iter).__name__
                break
            score = self.net.score_value
            if epoch % cfg.evaluate_every_n_epochs == 0:
                score = (cfg.score_calculator.calculate_score(self.net)
                         if cfg.score_calculator else self.net.score_value)
                scores[epoch] = score
                if score < best_score:
                    best_score, best_epoch = score, epoch
                    cfg.model_saver.save_best_model(self.net, score)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(self.net, score)
            # epoch terminations run EVERY epoch (reference
            # BaseEarlyStoppingTrainer checks them independently of the
            # score-calculation interval), using the most recent score
            stop_epoch = None
            for t in cfg.epoch_terminations:
                if t.terminate(epoch, score):
                    stop_epoch = t
                    break
            if stop_epoch is not None:
                reason = "EpochTermination"
                details = type(stop_epoch).__name__
                epoch += 1
                break
            epoch += 1
        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            score_vs_epoch=scores,
            best_model_epoch=best_epoch,
            best_model_score=best_score,
            total_epochs=epoch,
            best_model=cfg.model_saver.get_best_model(),
        )


class EarlyStoppingGraphTrainer(EarlyStoppingTrainer):
    """Same loop for ComputationGraph (reference EarlyStoppingGraphTrainer)."""
