"""NN core: configs, params, layers, containers (reference nn/ tree)."""
