"""JSON serde for config dataclasses.

The reference serializes configs with Jackson + a polymorphic subtype registry
(NeuralNetConfiguration.java:219-320, registerSubtypes:307-308) so stored JSON
round-trips through class hierarchies. Here every config dataclass registers
under a `@type` key; `to_dict`/`from_dict` walk nested dataclasses, enums,
lists and dicts. Custom layers register via `register_config`.
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any

_TYPE_KEY = "@type"
_REGISTRY: dict[str, type] = {}


def register_config(cls=None, *, name: str | None = None):
    """Class decorator: register a dataclass for polymorphic JSON round-trip."""

    def wrap(c):
        key = name or c.__name__
        _REGISTRY[key] = c
        c._serde_name = key
        return c

    return wrap(cls) if cls is not None else wrap


def to_dict(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj.value if isinstance(obj, enum.Enum) else obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {_TYPE_KEY: getattr(obj, "_serde_name", type(obj).__name__)}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            out[f.name] = to_dict(v)
        return out
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): to_dict(v) for k, v in obj.items()}
    raise TypeError(f"Cannot serialize {type(obj)!r} to config JSON")


def from_dict(data: Any) -> Any:
    if isinstance(data, dict) and _TYPE_KEY in data:
        cls = _REGISTRY.get(data[_TYPE_KEY])
        if cls is None:
            raise ValueError(f"Unknown config type '{data[_TYPE_KEY]}' — "
                             f"register custom configs with register_config")
        kwargs = {}
        field_names = {f.name for f in dataclasses.fields(cls)}
        for k, v in data.items():
            if k == _TYPE_KEY:
                continue
            if k in field_names:
                kwargs[k] = from_dict(v)
        obj = cls(**kwargs)
        return obj
    if isinstance(data, dict):
        return {k: from_dict(v) for k, v in data.items()}
    if isinstance(data, list):
        return [from_dict(v) for v in data]
    return data


def to_json(obj: Any, indent: int | None = 2) -> str:
    return json.dumps(to_dict(obj), indent=indent)


def from_json(s: str) -> Any:
    return from_dict(json.loads(s))
