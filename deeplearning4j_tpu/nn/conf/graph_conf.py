"""ComputationGraph configuration — string-keyed DAG wiring.

Reference: nn/conf/ComputationGraphConfiguration.java (`GraphBuilder`:446 —
addInputs:605, addLayer(name, layer, inputs...):569, addVertex:649,
setOutputs:633) and nn/conf/graph/* vertex configs (ElementWise, Merge,
Subset, Preprocessor, LastTimeStep, DuplicateToTimeSeries).

The DAG is declared as {name: (vertex_conf, [input names])}; at runtime the
ComputationGraph container topologically sorts it and traces the whole
forward into one jaxpr (SURVEY.md §3.2 TPU mapping).
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Optional

from deeplearning4j_tpu.nn.conf import serde
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer
from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
    BackpropType,
    NeuralNetConfiguration,
    _adapter,
    _expected_kind,
)
from deeplearning4j_tpu.nn.conf.preprocessors import InputPreProcessor


@serde.register_config
@dataclasses.dataclass
class GraphVertexConf:
    """Base vertex config (reference nn/conf/graph/GraphVertex.java)."""

    def get_output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]


@serde.register_config
@dataclasses.dataclass
class LayerVertexConf(GraphVertexConf):
    """Wraps any Layer config (reference graph/vertex/impl/LayerVertex.java)."""

    layer: Optional[Layer] = None
    preprocessor: Optional[InputPreProcessor] = None

    def get_output_type(self, *input_types: InputType) -> InputType:
        t = input_types[0]
        if self.preprocessor is not None:
            t = self.preprocessor.get_output_type(t)
        return self.layer.get_output_type(t)


@serde.register_config
@dataclasses.dataclass
class MergeVertexConf(GraphVertexConf):
    """Concatenate along the feature axis (reference MergeVertex)."""

    def get_output_type(self, *input_types: InputType) -> InputType:
        t0 = input_types[0]
        if t0.kind == "convolutional":
            ch = sum(t.channels for t in input_types)
            return InputType.convolutional(t0.height, t0.width, ch)
        size = sum(t.flat_size() for t in input_types)
        if t0.kind == "recurrent":
            return InputType.recurrent(size, t0.timeseries_length)
        return InputType.feed_forward(size)


@serde.register_config
@dataclasses.dataclass
class ElementWiseVertexConf(GraphVertexConf):
    """Elementwise Add/Subtract/Product/Average/Max (reference ElementWiseVertex)."""

    op: str = "add"  # add | subtract | product | average | max


@serde.register_config
@dataclasses.dataclass
class SubsetVertexConf(GraphVertexConf):
    """Feature-axis slice [from, to] inclusive (reference SubsetVertex)."""

    from_idx: int = 0
    to_idx: int = 0

    def get_output_type(self, *input_types: InputType) -> InputType:
        n = self.to_idx - self.from_idx + 1
        t0 = input_types[0]
        if t0.kind == "recurrent":
            return InputType.recurrent(n, t0.timeseries_length)
        return InputType.feed_forward(n)


@serde.register_config
@dataclasses.dataclass
class PreprocessorVertexConf(GraphVertexConf):
    preprocessor: Optional[InputPreProcessor] = None

    def get_output_type(self, *input_types: InputType) -> InputType:
        return self.preprocessor.get_output_type(input_types[0])


@serde.register_config
@dataclasses.dataclass
class LastTimeStepVertexConf(GraphVertexConf):
    """[batch, time, f] → [batch, f] taking the last (or last-unmasked)
    timestep (reference rnn/LastTimeStepVertex). The mask comes from the
    named input's mask array."""

    mask_input: Optional[str] = None

    def get_output_type(self, *input_types: InputType) -> InputType:
        return InputType.feed_forward(input_types[0].flat_size())


@serde.register_config
@dataclasses.dataclass
class DuplicateToTimeSeriesVertexConf(GraphVertexConf):
    """[batch, f] → [batch, time, f], time taken from a reference input
    (reference rnn/DuplicateToTimeSeriesVertex)."""

    reference_input: Optional[str] = None

    def get_output_type(self, *input_types: InputType) -> InputType:
        return InputType.recurrent(input_types[0].flat_size())


@serde.register_config
@dataclasses.dataclass
class ScaleVertexConf(GraphVertexConf):
    scale: float = 1.0


@serde.register_config
@dataclasses.dataclass
class StackVertexConf(GraphVertexConf):
    """Stack inputs along batch axis (reference StackVertex, later versions)."""


@serde.register_config
@dataclasses.dataclass
class UnstackVertexConf(GraphVertexConf):
    from_idx: int = 0
    stack_size: int = 1


@serde.register_config
@dataclasses.dataclass
class ComputationGraphConfiguration:
    """The DAG config (reference nn/conf/ComputationGraphConfiguration.java)."""

    conf: NeuralNetConfiguration = dataclasses.field(default_factory=NeuralNetConfiguration)
    network_inputs: list = dataclasses.field(default_factory=list)
    network_outputs: list = dataclasses.field(default_factory=list)
    vertices: dict = dataclasses.field(default_factory=dict)  # {name: vertex conf}
    vertex_inputs: dict = dataclasses.field(default_factory=dict)  # {name: [input names]}
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = BackpropType.STANDARD
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    input_types: dict = dataclasses.field(default_factory=dict)  # {input name: InputType}

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return serde.from_json(s)

    def topological_order(self) -> list:
        """Kahn topo sort over vertices (reference ComputationGraph.java:458-483)."""
        indeg = {}
        children = {name: [] for name in list(self.vertices) + list(self.network_inputs)}
        for name in self.vertices:
            ins = [i for i in self.vertex_inputs.get(name, [])]
            indeg[name] = len(ins)
            for i in ins:
                children.setdefault(i, []).append(name)
        order = []
        frontier = sorted(self.network_inputs)
        while frontier:
            n = frontier.pop()
            order.append(n)
            for c in children.get(n, []):
                indeg[c] -= 1
                if indeg[c] == 0:
                    frontier.append(c)
        if len(order) != len(self.vertices) + len(self.network_inputs):
            raise ValueError("Graph has a cycle or disconnected vertex inputs")
        return order


class GraphBuilder:
    """Reference ComputationGraphConfiguration.GraphBuilder:446."""

    def __init__(self, conf: NeuralNetConfiguration):
        self._g = ComputationGraphConfiguration(conf=conf)

    def add_inputs(self, *names) -> "GraphBuilder":
        self._g.network_inputs.extend(_flatten(names))
        return self

    def set_inputs(self, *names) -> "GraphBuilder":
        self._g.network_inputs = list(_flatten(names))
        return self

    def add_layer(self, name: str, layer: Layer, *inputs, preprocessor=None) -> "GraphBuilder":
        layer = self._g.conf.resolve_layer(layer)
        if layer.name is None:
            layer.name = name
        self._g.vertices[name] = LayerVertexConf(layer=layer, preprocessor=preprocessor)
        self._g.vertex_inputs[name] = list(_flatten(inputs))
        return self

    def add_vertex(self, name: str, vertex: GraphVertexConf, *inputs) -> "GraphBuilder":
        self._g.vertices[name] = vertex
        self._g.vertex_inputs[name] = list(_flatten(inputs))
        return self

    def set_outputs(self, *names) -> "GraphBuilder":
        self._g.network_outputs = list(_flatten(names))
        return self

    def backprop(self, flag: bool) -> "GraphBuilder":
        self._g.backprop = flag
        return self

    def pretrain(self, flag: bool) -> "GraphBuilder":
        self._g.pretrain = flag
        return self

    def backprop_type(self, t) -> "GraphBuilder":
        self._g.backprop_type = t
        return self

    def t_bptt_forward_length(self, n: int) -> "GraphBuilder":
        self._g.tbptt_fwd_length = n
        return self

    def t_bptt_backward_length(self, n: int) -> "GraphBuilder":
        self._g.tbptt_back_length = n
        return self

    def set_input_types(self, **types) -> "GraphBuilder":
        self._g.input_types.update(types)
        return self

    def build(self) -> ComputationGraphConfiguration:
        g = copy.deepcopy(self._g)
        if not g.network_inputs:
            raise ValueError("Graph needs addInputs(...)")
        if not g.network_outputs:
            raise ValueError("Graph needs setOutputs(...)")
        if g.input_types:
            _infer_graph_shapes(g)
        return g


def _infer_graph_shapes(g: ComputationGraphConfiguration):
    """Propagate InputTypes through topo order: set n_in, insert adapters."""
    types: dict[str, InputType] = dict(g.input_types)
    for name in g.topological_order():
        if name in g.network_inputs:
            if name not in types:
                raise ValueError(f"set_input_types missing for input '{name}'")
            continue
        v = g.vertices[name]
        in_types = [types[i] for i in g.vertex_inputs[name]]
        if isinstance(v, LayerVertexConf):
            t = in_types[0]
            if v.preprocessor is None:
                kind = _expected_kind(v.layer)
                v.preprocessor = _adapter(t, kind)
            if v.preprocessor is not None:
                t = v.preprocessor.get_output_type(t)
            v.layer.set_n_in(t)
            types[name] = v.layer.get_output_type(t)
        else:
            types[name] = v.get_output_type(*in_types)


def _flatten(xs):
    for x in xs:
        if isinstance(x, (list, tuple)):
            yield from x
        else:
            yield x
