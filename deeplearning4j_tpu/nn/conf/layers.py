"""Layer configuration dataclasses (reference conf/layers/* — 19 classes).

Each config is a declarative, JSON-serializable description; the matching
implementation (init + pure apply fn) lives in deeplearning4j_tpu/nn/layers/.
Fields left as None inherit the global defaults from the enclosing
NeuralNetConfiguration (reference Builder semantics:
NeuralNetConfiguration.java:338-373).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from deeplearning4j_tpu.nn.conf.distributions import Distribution
from deeplearning4j_tpu.nn.conf.enums import (
    ConvolutionMode,
    HiddenUnit,
    PoolingType,
    VisibleUnit,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.serde import register_config


@register_config
@dataclasses.dataclass
class Layer:
    """Base layer config (reference conf/layers/Layer.java)."""

    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    dist: Optional[Distribution] = None
    bias_init: Optional[float] = None
    dropout: Optional[float] = None
    drop_connect: Optional[bool] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    learning_rate: Optional[float] = None
    updater: Optional[str] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None

    # --- shape inference hooks (ConvolutionLayerSetup analogue) ---
    def set_n_in(self, input_type: InputType) -> None:  # noqa: B027
        """Infer and set n_in from the incoming InputType (no-op by default)."""

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type

    def is_pretrain_layer(self) -> bool:
        return False


@register_config
@dataclasses.dataclass
class FeedForwardLayer(Layer):
    """Base for layers with dense n_in→n_out params."""

    n_in: int = 0
    n_out: int = 0

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = input_type.flat_size()

    def get_output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "recurrent":
            return InputType.recurrent(self.n_out, input_type.timeseries_length)
        return InputType.feed_forward(self.n_out)


@register_config
@dataclasses.dataclass
class DenseLayer(FeedForwardLayer):
    """Fully-connected layer (reference layers/feedforward/dense/DenseLayer.java)."""


@register_config
@dataclasses.dataclass
class BaseOutputLayer(FeedForwardLayer):
    loss_function: str = "mcxent"

    def has_loss(self) -> bool:
        return True


@register_config
@dataclasses.dataclass
class OutputLayer(BaseOutputLayer):
    """Output layer with loss (reference conf/layers/OutputLayer.java)."""


@register_config
@dataclasses.dataclass
class RnnOutputLayer(BaseOutputLayer):
    """Per-timestep output layer (reference layers/recurrent/RnnOutputLayer.java).
    Input [batch, time, n_in] → output [batch, time, n_out]."""

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timeseries_length)


@register_config
@dataclasses.dataclass
class ActivationLayer(Layer):
    """Pure activation layer (reference conf/layers/ActivationLayer.java)."""


@register_config
@dataclasses.dataclass
class DropoutLayer(Layer):
    """Standalone dropout layer (TPU-build convenience)."""


@register_config
@dataclasses.dataclass
class BasePretrainNetwork(FeedForwardLayer):
    loss_function: str = "reconstruction_crossentropy"
    visible_bias_init: float = 0.0

    def is_pretrain_layer(self) -> bool:
        return True


@register_config
@dataclasses.dataclass
class AutoEncoder(BasePretrainNetwork):
    """Denoising autoencoder (reference layers/feedforward/autoencoder/AutoEncoder.java).
    corruption_level = input corruption probability; sparsity = KL target."""

    corruption_level: float = 0.3
    sparsity: float = 0.0


@register_config
@dataclasses.dataclass
class RBM(BasePretrainNetwork):
    """Restricted Boltzmann machine trained by CD-k (reference
    layers/feedforward/rbm/RBM.java: contrastiveDivergence:101, Gibbs
    sampling gibbhVh:149-151, unit types :197-205)."""

    hidden_unit: str = HiddenUnit.BINARY
    visible_unit: str = VisibleUnit.BINARY
    k: int = 1
    sparsity: float = 0.0


@register_config
@dataclasses.dataclass
class EmbeddingLayer(FeedForwardLayer):
    """Index → vector lookup (reference layers/feedforward/embedding/EmbeddingLayer.java).
    Input is int indices [batch] or [batch, 1]; lookup is a gather (one-hot
    matmul on MXU for small vocabularies)."""

    has_bias: bool = True


@register_config
@dataclasses.dataclass
class ConvolutionLayer(FeedForwardLayer):
    """2-D convolution (reference layers/convolution/ConvolutionLayer.java).

    The reference lowers conv to im2col+gemm (ConvolutionLayer.java:120-151);
    here it is a single `lax.conv_general_dilated` in NHWC which XLA maps
    directly onto the MXU. n_in = input channels, n_out = output channels.
    """

    kernel_size: tuple = (5, 5)
    stride: tuple = (1, 1)
    padding: tuple = (0, 0)
    convolution_mode: str = ConvolutionMode.STRICT
    dilation: tuple = (1, 1)

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in == 0 and input_type.kind in ("convolutional", "convolutional_flat"):
            self.n_in = input_type.channels

    def get_output_type(self, input_type: InputType) -> InputType:
        h, w = _conv_out_hw(
            input_type.height, input_type.width, self.kernel_size, self.stride,
            self.padding, self.convolution_mode, self.dilation,
        )
        return InputType.convolutional(h, w, self.n_out)


@register_config
@dataclasses.dataclass
class SubsamplingLayer(Layer):
    """Pooling layer (reference layers/convolution/subsampling/SubsamplingLayer.java;
    PoolingType at conf/layers/SubsamplingLayer.java:29-30). Lowors to
    `lax.reduce_window`."""

    pooling_type: str = PoolingType.MAX
    kernel_size: tuple = (2, 2)
    stride: tuple = (2, 2)
    padding: tuple = (0, 0)
    convolution_mode: str = ConvolutionMode.STRICT
    pnorm: int = 2

    def get_output_type(self, input_type: InputType) -> InputType:
        h, w = _conv_out_hw(
            input_type.height, input_type.width, self.kernel_size, self.stride,
            self.padding, self.convolution_mode, (1, 1),
        )
        return InputType.convolutional(h, w, input_type.channels)


@register_config
@dataclasses.dataclass
class BatchNormalization(FeedForwardLayer):
    """Batch normalization (reference layers/normalization/BatchNormalization.java:
    batch stats :191-193, gamma/beta :176-205, cumulative inference stats
    :196-197). Running stats live in the network's mutable `state` pytree,
    not in params — the functional-JAX idiom."""

    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0
    beta: float = 0.0
    lock_gamma_beta: bool = False

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in == 0:
            if input_type.kind in ("convolutional",):
                self.n_in = input_type.channels
            else:
                self.n_in = input_type.flat_size()
        if self.n_out == 0:
            self.n_out = self.n_in

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type


@register_config
@dataclasses.dataclass
class LocalResponseNormalization(Layer):
    """LRN across channels (reference layers/normalization/LocalResponseNormalization.java)."""

    k: float = 2.0
    n: float = 5.0
    alpha: float = 1e-4
    beta: float = 0.75


@register_config
@dataclasses.dataclass
class BaseRecurrentLayer(FeedForwardLayer):
    """Base for RNN layers; activations are [batch, time, features]."""

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timeseries_length)


@register_config
@dataclasses.dataclass
class GravesLSTM(BaseRecurrentLayer):
    """LSTM with peephole connections, per Graves (2013) — reference
    layers/recurrent/GravesLSTM.java + LSTMHelpers.java (fwd :50-180, bwd
    :210+; peephole params GravesLSTMParamInitializer.java:86-87).

    The per-timestep loop is a `lax.scan`; the 4 gates are one fused
    [n_in+n_out, 4*n_out] matmul per step. Backward is jax.grad through the
    scan (no hand-written BPTT)."""

    forget_gate_bias_init: float = 1.0


@register_config
@dataclasses.dataclass
class LSTM(BaseRecurrentLayer):
    """Standard LSTM without peepholes (TPU-era staple; cuDNN-compatible)."""

    forget_gate_bias_init: float = 1.0


@register_config
@dataclasses.dataclass
class GravesBidirectionalLSTM(BaseRecurrentLayer):
    """Bidirectional Graves LSTM (reference layers/recurrent/GravesBidirectionalLSTM.java).
    Output is the sum of forward and backward passes (reference merges by sum)."""

    forget_gate_bias_init: float = 1.0


@register_config
@dataclasses.dataclass
class GRU(BaseRecurrentLayer):
    """Gated recurrent unit (reference layers/recurrent/GRU.java)."""


@register_config
@dataclasses.dataclass
class LayerNormalization(FeedForwardLayer):
    """Layer norm over the feature axis — new capability for the Transformer
    north star (no reference analogue; SURVEY.md §7 step 6)."""

    eps: float = 1e-5

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = input_type.flat_size()
        if self.n_out == 0:
            self.n_out = self.n_in

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type


@register_config
@dataclasses.dataclass
class PositionalEncodingLayer(Layer):
    """Adds positional information to [batch, time, features] — sinusoidal
    (param-free) or learned. New capability for the Transformer north star."""

    learned: bool = False
    max_length: int = 2048
    n_features: int = 0
    # inside a sequence-parallel shard_map (see SelfAttentionLayer), each
    # shard holds rows [idx*Tl, (idx+1)*Tl) of the sequence: offset the
    # encodings by the shard's global position
    seq_parallel_axis: str = ""

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_features == 0:
            self.n_features = input_type.flat_size()

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type


@register_config
@dataclasses.dataclass
class SelfAttentionLayer(BaseRecurrentLayer):
    """Multi-head self-attention over [batch, time, features] — new capability
    for the Transformer north star (SURVEY.md §7 step 6). Supports causal
    masking and optional ring-attention sequence parallelism (parallel/)."""

    n_heads: int = 8
    causal: bool = True
    attention_dropout: float = 0.0
    use_flash: bool = True  # fused Pallas kernel when the case supports it
    # when set, the layer runs INSIDE shard_map over a mesh axis of this
    # name with the time dimension sharded: attention becomes the ppermute
    # ring (parallel/ring_attention.py) so each shard only ever holds its
    # local K/V block — the sequence-parallel training path
    # (parallel/sequence_parallel.py)
    seq_parallel_axis: str = ""

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = input_type.flat_size()
        if self.n_out == 0:
            self.n_out = self.n_in


def _conv_out_hw(h, w, kernel, stride, padding, mode, dilation):
    kh = (kernel[0] - 1) * dilation[0] + 1
    kw = (kernel[1] - 1) * dilation[1] + 1
    if mode == ConvolutionMode.SAME or mode == "same":
        return -(-h // stride[0]), -(-w // stride[1])
    if mode == ConvolutionMode.VALID or mode == "valid":
        return (h - kh) // stride[0] + 1, (w - kw) // stride[1] + 1
    return (
        (h + 2 * padding[0] - kh) // stride[0] + 1,
        (w + 2 * padding[1] - kw) // stride[1] + 1,
    )


def validate_layer_names(layer_conf) -> None:
    """Eagerly resolve a layer conf's string-named activation / loss so a
    typo'd name fails at init() with a named ValueError instead of at first
    trace (the reference fails at conf time via its enums)."""
    from deeplearning4j_tpu.ops.activations import get_activation
    from deeplearning4j_tpu.ops.losses import validate_loss

    act = getattr(layer_conf, "activation", None)
    if act is not None:
        get_activation(act)
    loss = getattr(layer_conf, "loss_function", None)
    if loss is not None:
        validate_loss(loss)
