"""NeuralNetConfiguration — the builder-style declarative config API.

Mirrors the reference's user-facing surface (NeuralNetConfiguration.java:338
Builder: activation default "sigmoid" :339, WeightInit.XAVIER :340, lr 1e-1
:343, Updater.SGD :350, iterations :360, optimizationAlgo :364;
MultiLayerConfiguration.java: backprop/pretrain flags, TBPTT lengths default
20 :55-56) while being a plain dataclass tree that JSON round-trips
(serde.py replaces the Jackson subtype registry).

Global hyperparameters set on the Builder are inherited by every layer that
does not override them (`resolve_layer` applies the inheritance) — the same
semantics as the reference's per-layer override model.

TPU-first additions: `dtype`/`param_dtype` (bf16 compute / f32 params mixed
precision) and `accum_dtype` — the reference is implicitly f32-only.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Optional

from deeplearning4j_tpu.nn.conf import serde
from deeplearning4j_tpu.nn.conf.distributions import Distribution
from deeplearning4j_tpu.nn.conf.enums import (
    BackpropType,
    GradientNormalization,
    LearningRatePolicy,
    OptimizationAlgorithm,
    Updater,
    WeightInit,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BaseRecurrentLayer,
    BatchNormalization,
    ConvolutionLayer,
    Layer,
    LocalResponseNormalization,
    RnnOutputLayer,
    SelfAttentionLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor,
    FeedForwardToCnnPreProcessor,
    FeedForwardToRnnPreProcessor,
    InputPreProcessor,
    RnnToFeedForwardPreProcessor,
)


@serde.register_config
@dataclasses.dataclass
class NeuralNetConfiguration:
    """Global (defaults) section of a network config."""

    seed: int = 12345
    optimization_algo: str = OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT
    iterations: int = 1  # optimizer passes per minibatch (reference :360)
    learning_rate: float = 1e-1  # reference default :343
    bias_learning_rate: Optional[float] = None
    lr_policy: str = LearningRatePolicy.NONE
    lr_policy_decay_rate: float = 0.0
    lr_policy_steps: float = 0.0
    lr_policy_power: float = 0.0
    lr_schedule: Optional[dict] = None  # {iteration: lr}
    warmup_steps: int = 0
    decay_steps: int = 0
    momentum: float = 0.5
    momentum_schedule: Optional[dict] = None
    rho: float = 0.95  # adadelta
    rms_decay: float = 0.95
    adam_mean_decay: float = 0.9
    adam_var_decay: float = 0.999
    epsilon: float = 1e-8
    updater: str = Updater.SGD
    weight_decay: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    dropout: float = 0.0
    use_drop_connect: bool = False
    weight_init: str = WeightInit.XAVIER
    dist: Optional[Distribution] = None
    bias_init: float = 0.0
    activation: str = "sigmoid"  # reference default :339
    gradient_normalization: str = GradientNormalization.NONE
    gradient_normalization_threshold: float = 1.0
    minimize: bool = True
    max_num_line_search_iterations: int = 5
    step_function: Optional[str] = None
    mini_batch: bool = True
    # --- TPU-first additions ---
    dtype: str = "float32"  # compute dtype ("bfloat16" for MXU-friendly)
    param_dtype: str = "float32"
    remat: bool = False  # jax.checkpoint the forward (HBM↔FLOPs tradeoff)

    @staticmethod
    def builder() -> "Builder":
        return Builder()

    # -- inheritance: fill a layer's None fields from these globals --
    _INHERITED = (
        "activation", "weight_init", "dist", "bias_init", "dropout", "l1",
        "l2", "learning_rate", "updater", "gradient_normalization",
        "gradient_normalization_threshold",
    )

    def resolve_layer(self, layer: Layer) -> Layer:
        layer = copy.deepcopy(layer)
        for f in self._INHERITED:
            if getattr(layer, f, None) is None:
                if f == "learning_rate":
                    layer.learning_rate = None  # None = use global schedule
                elif f == "drop_connect":
                    layer.drop_connect = self.use_drop_connect
                else:
                    setattr(layer, f, getattr(self, f, None))
        if getattr(layer, "drop_connect", None) is None:
            layer.drop_connect = self.use_drop_connect
        return layer

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "NeuralNetConfiguration":
        return serde.from_json(s)


@serde.register_config
@dataclasses.dataclass
class MultiLayerConfiguration:
    """Sequential-stack config (reference nn/conf/MultiLayerConfiguration.java)."""

    conf: NeuralNetConfiguration = dataclasses.field(default_factory=NeuralNetConfiguration)
    layers: list = dataclasses.field(default_factory=list)
    input_pre_processors: dict = dataclasses.field(default_factory=dict)  # {str(idx): proc}
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = BackpropType.STANDARD
    tbptt_fwd_length: int = 20  # reference MultiLayerConfiguration.java:55-56
    tbptt_back_length: int = 20
    input_type: Optional[InputType] = None

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return serde.from_json(s)

    def get_preprocessor(self, idx: int):
        return self.input_pre_processors.get(str(idx))


class Builder:
    """Fluent builder matching NeuralNetConfiguration.Builder's method surface.

    Methods are snake_case; each returns self. `.list()` moves to layer
    wiring (ListBuilder), `.graph_builder()` to DAG wiring.
    """

    def __init__(self):
        self._c = NeuralNetConfiguration()

    # Generic setter generation keeps the surface complete without boilerplate.
    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in NeuralNetConfiguration.__dataclass_fields__:
            def setter(value):
                setattr(self._c, name, _coerce_enum(value))
                return self
            return setter
        raise AttributeError(
            f"No such config field '{name}'. Fields: "
            f"{sorted(NeuralNetConfiguration.__dataclass_fields__)}"
        )

    # Explicit aliases matching reference naming
    def optimization_algo(self, v):
        from deeplearning4j_tpu.nn.conf.enums import OptimizationAlgorithm

        v = _coerce_enum(v)
        try:
            v = OptimizationAlgorithm(v)
        except ValueError:
            raise ValueError(
                f"Unknown optimization algorithm {v!r}; one of "
                f"{[a.value for a in OptimizationAlgorithm]}") from None
        self._c.optimization_algo = str(v)
        return self

    def regularization(self, flag: bool):
        # reference's use-regularization toggle: off zeroes l1/l2
        if not flag:
            self._c.l1 = 0.0
            self._c.l2 = 0.0
        return self

    def build(self) -> NeuralNetConfiguration:
        return copy.deepcopy(self._c)

    def list(self) -> "ListBuilder":
        return ListBuilder(self.build())

    def graph_builder(self):
        from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder

        return GraphBuilder(self.build())


class ListBuilder:
    """Layer-stack wiring (reference NeuralNetConfiguration.ListBuilder)."""

    def __init__(self, conf: NeuralNetConfiguration):
        self._conf = conf
        self._layers: list[Layer] = []
        self._preprocessors: dict[int, InputPreProcessor] = {}
        self._backprop = True
        self._pretrain = False
        self._backprop_type = BackpropType.STANDARD
        self._tbptt_fwd = 20
        self._tbptt_back = 20
        self._input_type: Optional[InputType] = None

    def layer(self, idx_or_layer, layer: Optional[Layer] = None) -> "ListBuilder":
        if layer is None:
            self._layers.append(idx_or_layer)
        else:
            idx = idx_or_layer
            while len(self._layers) <= idx:
                self._layers.append(None)
            self._layers[idx] = layer
        return self

    def input_pre_processor(self, idx: int, proc: InputPreProcessor) -> "ListBuilder":
        self._preprocessors[idx] = proc
        return self

    def backprop(self, flag: bool) -> "ListBuilder":
        self._backprop = flag
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._pretrain = flag
        return self

    def backprop_type(self, t) -> "ListBuilder":
        self._backprop_type = _coerce_enum(t)
        return self

    def t_bptt_forward_length(self, n: int) -> "ListBuilder":
        self._tbptt_fwd = n
        return self

    def t_bptt_backward_length(self, n: int) -> "ListBuilder":
        self._tbptt_back = n
        return self

    def set_input_type(self, t: InputType) -> "ListBuilder":
        self._input_type = t
        return self

    # alias matching reference's ConvolutionLayerSetup usage
    input_type = set_input_type

    def build(self) -> MultiLayerConfiguration:
        if any(l is None for l in self._layers):
            raise ValueError("Layer list has gaps — set every index")
        layers = [self._conf.resolve_layer(l) for l in self._layers]
        _validate_names(layers)
        pre = {int(k): v for k, v in self._preprocessors.items()}
        if self._input_type is not None:
            _infer_shapes(layers, pre, self._input_type)
        mlc = MultiLayerConfiguration(
            conf=self._conf,
            layers=layers,
            input_pre_processors={str(k): v for k, v in pre.items()},
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            input_type=self._input_type,
        )
        return mlc


def _validate_names(layers) -> None:
    """Fail fast at build() on typo'd activation/loss names instead of at
    init() — the builder is the user-facing contract (reference builders
    validate eagerly via enums)."""
    from deeplearning4j_tpu.nn.conf.layers import validate_layer_names

    for i, layer in enumerate(layers):
        try:
            validate_layer_names(layer)
        except ValueError as e:
            raise ValueError(f"layer {i} ({type(layer).__name__}): {e}") from None


def _expected_kind(layer: Layer) -> str:
    from deeplearning4j_tpu.nn.conf.layers import (
        ActivationLayer,
        DropoutLayer,
        PositionalEncodingLayer,
    )

    if isinstance(layer, (ConvolutionLayer, SubsamplingLayer, LocalResponseNormalization)):
        return "convolutional"
    if isinstance(layer, (BaseRecurrentLayer, RnnOutputLayer, SelfAttentionLayer)):
        return "recurrent"
    if isinstance(layer, (BatchNormalization, ActivationLayer, DropoutLayer,
                          PositionalEncodingLayer)):
        return "any"  # shape-preserving: accept any input kind
    return "feedforward"


def _adapter(from_type: InputType, to_kind: str):
    """Auto-insert shape adapters (reference ConvolutionLayerSetup behavior)."""
    if to_kind in ("any",) or from_type.kind == to_kind:
        return None
    if from_type.kind == "convolutional_flat" and to_kind == "convolutional":
        return FeedForwardToCnnPreProcessor(
            height=from_type.height, width=from_type.width, channels=from_type.channels
        )
    if from_type.kind == "convolutional_flat" and to_kind == "feedforward":
        return None  # already flat
    if from_type.kind == "convolutional" and to_kind == "feedforward":
        return CnnToFeedForwardPreProcessor(
            height=from_type.height, width=from_type.width, channels=from_type.channels
        )
    if from_type.kind == "feedforward" and to_kind == "convolutional":
        raise ValueError(
            "Cannot infer CNN shape from a flat feed-forward input; set an "
            "explicit FeedForwardToCnnPreProcessor"
        )
    if from_type.kind == "feedforward" and to_kind == "recurrent":
        return FeedForwardToRnnPreProcessor()
    if from_type.kind == "recurrent" and to_kind == "feedforward":
        return RnnToFeedForwardPreProcessor()
    if from_type.kind == "convolutional" and to_kind == "recurrent":
        from deeplearning4j_tpu.nn.conf.preprocessors import CnnToRnnPreProcessor

        return CnnToRnnPreProcessor()
    raise ValueError(f"No adapter {from_type.kind} → {to_kind}")


def _infer_shapes(layers, preprocessors, input_type: InputType):
    """Propagate InputType through the stack: set n_in everywhere, and insert
    preprocessors where layer kinds change (ConvolutionLayerSetup.java analogue)."""
    cur = input_type
    for i, layer in enumerate(layers):
        kind = _expected_kind(layer)
        proc = preprocessors.get(i)
        if proc is None:
            proc = _adapter(cur, kind)
            if proc is not None:
                preprocessors[i] = proc
        if proc is not None:
            cur = proc.get_output_type(cur)
        layer.set_n_in(cur)
        cur = layer.get_output_type(cur)


def _coerce_enum(v):
    import enum as _enum

    if isinstance(v, _enum.Enum):
        return v.value
    return v
