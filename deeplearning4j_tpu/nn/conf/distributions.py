"""Weight distributions (reference conf/distribution/*: Normal, Uniform,
Binomial, Gaussian)."""

from __future__ import annotations

import dataclasses

import jax

from deeplearning4j_tpu.nn.conf.serde import register_config


@register_config
@dataclasses.dataclass
class Distribution:
    def sample(self, rng, shape, dtype):
        raise NotImplementedError


@register_config
@dataclasses.dataclass
class NormalDistribution(Distribution):
    """Gaussian with given mean/std (reference NormalDistribution)."""

    mean: float = 0.0
    std: float = 1.0

    def sample(self, rng, shape, dtype):
        return self.mean + self.std * jax.random.normal(rng, shape, dtype)


# The reference has both GaussianDistribution and NormalDistribution (aliases).
GaussianDistribution = register_config(name="GaussianDistribution")(
    dataclasses.make_dataclass(
        "GaussianDistribution", [("mean", float, 0.0), ("std", float, 1.0)],
        bases=(NormalDistribution,),
    )
)


@register_config
@dataclasses.dataclass
class UniformDistribution(Distribution):
    lower: float = -1.0
    upper: float = 1.0

    def sample(self, rng, shape, dtype):
        return jax.random.uniform(
            rng, shape, dtype, minval=self.lower, maxval=self.upper
        )


@register_config
@dataclasses.dataclass
class BinomialDistribution(Distribution):
    number_of_trials: int = 1
    probability_of_success: float = 0.5

    def sample(self, rng, shape, dtype):
        return jax.random.binomial(
            rng, self.number_of_trials, self.probability_of_success, shape
        ).astype(dtype)
