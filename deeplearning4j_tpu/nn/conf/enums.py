"""Config enums mirroring the reference's nn/conf enums.

Reference: Updater.java (SGD, ADAM, ADADELTA, NESTEROVS, ADAGRAD, RMSPROP,
NONE, CUSTOM), OptimizationAlgorithm.java, GradientNormalization.java,
LearningRatePolicy.java, BackpropType.java, WeightInit.java,
conf/layers/SubsamplingLayer.java:29-30 (PoolingType).
Values are plain strings so configs JSON-serialize trivially.
"""

from __future__ import annotations

import enum


class StrEnum(str, enum.Enum):
    def __str__(self):  # serialize as bare string
        return self.value


class Updater(StrEnum):
    SGD = "sgd"
    ADAM = "adam"
    ADAMW = "adamw"
    ADADELTA = "adadelta"
    NESTEROVS = "nesterovs"
    ADAGRAD = "adagrad"
    RMSPROP = "rmsprop"
    LION = "lion"
    LAMB = "lamb"
    NONE = "none"
    CUSTOM = "custom"


class OptimizationAlgorithm(StrEnum):
    LINE_GRADIENT_DESCENT = "line_gradient_descent"
    CONJUGATE_GRADIENT = "conjugate_gradient"
    HESSIAN_FREE = "hessian_free"
    LBFGS = "lbfgs"
    STOCHASTIC_GRADIENT_DESCENT = "stochastic_gradient_descent"


class WeightInit(StrEnum):
    """Reference nn/weights/WeightInit.java: DISTRIBUTION, NORMALIZED, SIZE,
    UNIFORM, VI, ZERO, XAVIER, RELU."""

    DISTRIBUTION = "distribution"
    NORMALIZED = "normalized"
    SIZE = "size"
    UNIFORM = "uniform"
    VI = "vi"
    ZERO = "zero"
    XAVIER = "xavier"
    RELU = "relu"
    LECUN = "lecun"


class GradientNormalization(StrEnum):
    NONE = "none"
    RENORMALIZE_L2_PER_LAYER = "renormalize_l2_per_layer"
    RENORMALIZE_L2_PER_PARAM_TYPE = "renormalize_l2_per_param_type"
    CLIP_ELEMENTWISE_ABSOLUTE_VALUE = "clip_elementwise_absolute_value"
    CLIP_L2_PER_LAYER = "clip_l2_per_layer"
    CLIP_L2_PER_PARAM_TYPE = "clip_l2_per_param_type"


class LearningRatePolicy(StrEnum):
    NONE = "none"
    EXPONENTIAL = "exponential"
    INVERSE = "inverse"
    POLY = "poly"
    SIGMOID = "sigmoid"
    STEP = "step"
    TORCH_STEP = "torch_step"
    SCHEDULE = "schedule"
    COSINE = "cosine"  # TPU-era addition (not in reference)
    WARMUP_COSINE = "warmup_cosine"  # TPU-era addition


class BackpropType(StrEnum):
    STANDARD = "standard"
    TRUNCATED_BPTT = "truncated_bptt"


class PoolingType(StrEnum):
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    NONE = "none"
    PNORM = "pnorm"


class ConvolutionMode(StrEnum):
    """Padding semantics; reference pads explicitly — SAME/VALID are the XLA idiom."""

    STRICT = "strict"  # explicit padding, error on non-exact fit
    SAME = "same"
    VALID = "valid"


class HiddenUnit(StrEnum):
    """RBM hidden unit types (reference layers/feedforward/rbm/RBM.java:197-205)."""

    BINARY = "binary"
    GAUSSIAN = "gaussian"
    RECTIFIED = "rectified"
    SOFTMAX = "softmax"


class VisibleUnit(StrEnum):
    BINARY = "binary"
    GAUSSIAN = "gaussian"
    LINEAR = "linear"
    SOFTMAX = "softmax"
