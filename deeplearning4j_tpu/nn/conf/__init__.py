"""Declarative network configuration (reference nn/conf)."""

from deeplearning4j_tpu.nn.conf.enums import (  # noqa: F401
    BackpropType,
    ConvolutionMode,
    GradientNormalization,
    HiddenUnit,
    LearningRatePolicy,
    OptimizationAlgorithm,
    PoolingType,
    Updater,
    VisibleUnit,
    WeightInit,
)
from deeplearning4j_tpu.nn.conf.distributions import (  # noqa: F401
    BinomialDistribution,
    Distribution,
    GaussianDistribution,
    NormalDistribution,
    UniformDistribution,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
from deeplearning4j_tpu.nn.conf.layers import (  # noqa: F401
    ActivationLayer,
    AutoEncoder,
    BaseOutputLayer,
    BasePretrainNetwork,
    BaseRecurrentLayer,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    FeedForwardLayer,
    GravesBidirectionalLSTM,
    GravesLSTM,
    GRU,
    Layer,
    LayerNormalization,
    LocalResponseNormalization,
    LSTM,
    OutputLayer,
    RBM,
    RnnOutputLayer,
    SelfAttentionLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.neural_net_configuration import (  # noqa: F401
    Builder,
    ListBuilder,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.conf.graph_conf import (  # noqa: F401
    ComputationGraphConfiguration,
    DuplicateToTimeSeriesVertexConf,
    ElementWiseVertexConf,
    GraphBuilder,
    GraphVertexConf,
    LastTimeStepVertexConf,
    LayerVertexConf,
    MergeVertexConf,
    PreprocessorVertexConf,
    ScaleVertexConf,
    SubsetVertexConf,
)
from deeplearning4j_tpu.nn.conf.preprocessors import (  # noqa: F401
    CnnToFeedForwardPreProcessor,
    CnnToRnnPreProcessor,
    ComposableInputPreProcessor,
    FeedForwardToCnnPreProcessor,
    FeedForwardToRnnPreProcessor,
    InputPreProcessor,
    ReshapePreProcessor,
    RnnToCnnPreProcessor,
    RnnToFeedForwardPreProcessor,
)
from deeplearning4j_tpu.nn.conf.serde import (  # noqa: F401
    from_dict,
    from_json,
    register_config,
    to_dict,
    to_json,
)
