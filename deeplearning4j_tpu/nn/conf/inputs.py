"""InputType — shape inference metadata (reference conf/inputs/InputType.java).

Drives automatic n_in inference and automatic preprocessor insertion
(reference conf/layers/setup/ConvolutionLayerSetup.java).

TPU-first layout decisions (differ deliberately from the reference):
- convolutional activations are NHWC (TPU/XLA-preferred), not NCHW
- recurrent activations are [batch, time, features], not [batch, features, time]
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.serde import register_config


@register_config
@dataclasses.dataclass
class InputType:
    kind: str = "feedforward"  # feedforward | recurrent | convolutional | convolutional_flat
    size: int = 0  # feedforward/recurrent feature size
    height: int = 0
    width: int = 0
    channels: int = 0
    timeseries_length: int = -1  # -1 = variable

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="feedforward", size=size)

    @staticmethod
    def recurrent(size: int, timeseries_length: int = -1) -> "InputType":
        return InputType(kind="recurrent", size=size, timeseries_length=timeseries_length)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutional", height=height, width=width, channels=channels)

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType(
            kind="convolutional_flat", height=height, width=width, channels=channels,
            size=height * width * channels,
        )

    def flat_size(self) -> int:
        if self.kind in ("feedforward", "recurrent"):
            return self.size
        return self.height * self.width * self.channels
