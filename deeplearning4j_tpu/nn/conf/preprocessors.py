"""Input preprocessors — shape adapters between layer kinds.

Reference: conf/preprocessor/* (13 adapters: CnnToFeedForward,
FeedForwardToCnn, FeedForwardToRnn, RnnToFeedForward, CnnToRnn, RnnToCnn,
...). Each is a pure reshape/transpose; jax.grad differentiates through
them so there is no hand-written backprop() method as in the reference.

Layouts (TPU-first, see conf/inputs.py): CNN = NHWC, RNN = [batch, time, f].
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.serde import register_config


@register_config
@dataclasses.dataclass
class InputPreProcessor:
    def pre_process(self, x):
        return x

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type


@register_config
@dataclasses.dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x):
        return x.reshape(x.shape[0], -1)

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.flat_size())


@register_config
@dataclasses.dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 1

    def pre_process(self, x):
        if x.ndim == 4:
            return x
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@register_config
@dataclasses.dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[batch*time, f] → [batch, time, f] is impossible without time; here the
    network keeps RNN activations 3-D throughout, so this adapter broadcasts
    a 2-D input to a single-timestep sequence."""

    def pre_process(self, x):
        if x.ndim == 3:
            return x
        return x[:, None, :]

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.flat_size())


@register_config
@dataclasses.dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[batch, time, f] → applied per-timestep: dense layers operate on the
    last axis, so this is an identity marker kept for reference parity
    (the reference reshapes to [batch*time, f] — RnnToFeedForwardPreProcessor)."""

    def pre_process(self, x):
        return x

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.flat_size())


@register_config
@dataclasses.dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    def pre_process(self, x):
        # NHWC → [batch, 1, h*w*c]: a CNN frame becomes one timestep
        return x.reshape(x.shape[0], 1, -1)

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.flat_size())


@register_config
@dataclasses.dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 1

    def pre_process(self, x):
        # [batch, time, f] → fold time into batch → NHWC
        b, t, f = x.shape
        return x.reshape(b * t, self.height, self.width, self.channels)

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@register_config
@dataclasses.dataclass
class ReshapePreProcessor(InputPreProcessor):
    """Generic reshape (keeps batch dim)."""

    shape: tuple = ()

    def pre_process(self, x):
        return x.reshape((x.shape[0],) + tuple(self.shape))


@register_config
@dataclasses.dataclass
class ComposableInputPreProcessor(InputPreProcessor):
    """Chain of preprocessors (reference ComposableInputPreProcessor)."""

    processors: list = dataclasses.field(default_factory=list)

    def pre_process(self, x):
        for p in self.processors:
            x = p.pre_process(x)
        return x

    def get_output_type(self, input_type: InputType) -> InputType:
        for p in self.processors:
            input_type = p.get_output_type(input_type)
        return input_type


@register_config
@dataclasses.dataclass
class BinomialSamplingPreProcessor(InputPreProcessor):
    """Reference BinomialSamplingPreProcessor — kept as identity + note;
    stochastic binarization is applied in the RBM layer itself with keyed RNG."""

    def pre_process(self, x):
        return jnp.clip(x, 0.0, 1.0)
