"""MultiLayerNetwork — the sequential-stack container.

Reference: nn/multilayer/MultiLayerNetwork.java (2,367 LoC): init:349,
fit(DataSetIterator):1011, pretrain:165, feedForward:614, backprop:1065,
computeGradientAndScore:1781, doTruncatedBPTT, rnnTimeStep:2147,
evaluate:2311, output:1500-1582, setLayerMaskArrays.

TPU-native redesign:
- params/state/opt_state are pytrees keyed by layer name (the reference's
  flat 1×N view vector with per-layer views is replaced by the pytree
  idiom; `params_flat`/`set_params_flat` provide the flat view for
  parameter-averaging parity and serialization)
- forward/backward/update is ONE jitted donated XLA computation
  (SURVEY.md §3.1 TPU mapping); jax.grad replaces calcBackpropGradients
- fit rides the async input pipeline (data/pipeline.iter_prefetched):
  batch conversion + device placement run on a prefetch thread feeding
  a bounded queue of device-resident batches, replacing the reference's
  AsyncDataSetIterator wrap (MultiLayerNetwork.fit:1014) with
  conversion overlap, not just host-IO overlap
- TBPTT runs the jitted step per truncation segment with explicit RNN
  carries (stop-gradient between segments)
- rnnTimeStep keeps a carry pytree on the host between calls
"""

from __future__ import annotations

import copy
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.enums import BackpropType, OptimizationAlgorithm
from deeplearning4j_tpu.nn.conf.layers import (
    BaseOutputLayer,
    BaseRecurrentLayer,
    RnnOutputLayer,
    validate_layer_names,
)
from deeplearning4j_tpu.nn.conf.neural_net_configuration import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers import get_impl, l1_l2_penalty
from deeplearning4j_tpu.nn.layers.base import pop_aux_losses
from deeplearning4j_tpu.nn.training import make_train_step, tree_cast
from deeplearning4j_tpu.nn.updater import build_optimizer

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float64": jnp.float64,
           "float16": jnp.float16}


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layer_confs = list(conf.layers)
        self.layer_names = [
            lc.name if lc.name else f"layer_{i}" for i, lc in enumerate(self.layer_confs)
        ]
        self.impls = [get_impl(lc) for lc in self.layer_confs]
        self.params = None
        self.state = None
        self.opt_state = None
        self.tx = None
        self.listeners = []
        self.iteration_count = 0
        self.epoch_count = 0
        self._train_step = None
        self._scan_fit = None
        self._output_jit = None
        self._score_examples_jit = {}
        self._rng = None
        self._rnn_carries = None  # streaming inference state
        self._rnn_jit = None
        self._mesh = None
        self._zero1 = False
        self._multiprocess = False
        self.score_value = float("nan")

    # ------------------------------------------------------------------ init
    @property
    def param_dtype(self):
        return _DTYPES[self.conf.conf.param_dtype]

    @property
    def compute_dtype(self):
        return _DTYPES[self.conf.conf.dtype]

    def init(self, seed: Optional[int] = None):
        """Allocate parameters (reference init:349)."""
        g = self.conf.conf
        key = jax.random.PRNGKey(g.seed if seed is None else seed)
        self._rng = jax.random.fold_in(key, 1)
        params, state = {}, {}
        for lc in self.layer_confs:
            validate_layer_names(lc)
        keys = jax.random.split(key, max(len(self.layer_confs), 1))
        for name, lc, impl, k in zip(self.layer_names, self.layer_confs, self.impls, keys):
            p, s = impl.init(lc, k, self.param_dtype)
            params[name] = p
            state[name] = s
        self.params = params
        self.state = state
        self.tx = build_optimizer(g, dict(zip(self.layer_names, self.layer_confs)),
                                  params=params)
        self.opt_state = self.tx.init(params)
        return self

    def set_optimizer(self, tx: optax.GradientTransformation):
        """Custom updater hook (reference Updater.CUSTOM)."""
        self.tx = tx
        self.opt_state = tx.init(self.params)
        self._train_step = None
        self._scan_fit = None
        self._output_jit = None
        self._score_examples_jit = {}

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)

    def set_mesh(self, mesh, zero1: bool = False, axes=None,
                 n_microbatches=None, tp_rules=None, overlap=None):
        """Enable distributed training over a jax.sharding.Mesh (replaces
        the Spark parameter-averaging master). axes maps parallelism roles
        ("data"/"model"/"expert"; "pipe" needs the graph container) to mesh
        axis names — see parallel/placement.py. Without axes: pure DP over
        a 'data' axis. overlap: True / bucket bytes / a BucketPlan —
        bucketed gradient allreduce with compute/communication overlap
        (parallel/overlap.py; pure DP only, composes with zero1)."""
        from deeplearning4j_tpu.parallel.placement import configure_mesh

        return configure_mesh(self, mesh, zero1=zero1, axes=axes,
                              n_microbatches=n_microbatches,
                              tp_rules=tp_rules, overlap=overlap)

    # --------------------------------------------------------------- forward
    def _next_rng(self):
        self._rng, k = jax.random.split(self._rng)
        return k

    def _forward(self, params, state, x, *, train, rng, mask=None,
                 carries=None, collect=False, to_layer=None):
        """Walk the stack (reference feedForwardToLayer:637). Returns
        (activations list if collect else final activation, new_state,
        new_carries)."""
        g = self.conf.conf
        cdtype = self.compute_dtype
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            x = jnp.asarray(x, cdtype)
        acts = []
        new_state = {}
        new_carries = {}
        n_layers = len(self.layer_confs) if to_layer is None else to_layer
        rngs = (jax.random.split(rng, max(n_layers, 1)) if rng is not None
                else [None] * n_layers)
        for i in range(n_layers):
            name, lc, impl = self.layer_names[i], self.layer_confs[i], self.impls[i]
            proc = self.conf.get_preprocessor(i)
            if proc is not None:
                x = proc.pre_process(x)
            p = params.get(name, {})
            if cdtype != self.param_dtype:
                p = tree_cast(p, cdtype)
            want_carry = (carries is not None and isinstance(lc, BaseRecurrentLayer)
                          and hasattr(impl, "initial_carry"))

            def run(p_, s_, x_, _impl=impl, _lc=lc, _rng=rngs[i], _wc=want_carry,
                    _carry=(carries.get(name) if want_carry else None)):
                kw = {"initial_carry": _carry, "return_carry": True} if _wc else {}
                return _impl.apply(_lc, p_, s_, x_, train=train, rng=_rng,
                                   mask=mask, **kw)

            if g.remat:
                run = jax.checkpoint(run)
            out = run(p, state.get(name, {}), x)
            if want_carry:
                x, s, carry = out
                new_carries[name] = carry
            else:
                x, s = out
            new_state[name] = s
            if collect:
                acts.append(x)
        # passthrough state for layers beyond to_layer
        for j in range(n_layers, len(self.layer_confs)):
            new_state[self.layer_names[j]] = state.get(self.layer_names[j], {})
        if collect:
            return acts, new_state, new_carries
        return x, new_state, new_carries

    def _loss(self, params, state, rng, batch, train=True):
        """Forward to the output layer's loss + L1/L2 (reference
        computeGradientAndScore:1781). Returns (loss, (new_state, extras));
        extras holds RNN carries when batch supplies `carries` (TBPTT)."""
        x = batch["features"]
        labels = batch["labels"]
        fmask = batch.get("features_mask")
        lmask = batch.get("labels_mask")
        carries = batch.get("carries")
        out_conf = self.layer_confs[-1]
        if not isinstance(out_conf, BaseOutputLayer):
            raise ValueError("Last layer must be an OutputLayer to compute a score")
        n = len(self.layer_confs)
        k_body, k_out = (jax.random.split(rng) if rng is not None else (None, None))
        h, new_state, new_carries = self._forward(
            params, state, x, train=train, rng=k_body, mask=fmask,
            carries=carries, to_layer=n - 1)
        proc = self.conf.get_preprocessor(n - 1)
        if proc is not None:
            h = proc.pre_process(h)
        out_impl = self.impls[-1]
        out_name = self.layer_names[-1]
        mask = lmask if lmask is not None else (
            fmask if isinstance(out_conf, RnnOutputLayer) else None)
        # cast output-layer params to the compute dtype like _forward does
        # for the body — a bf16 model must not stream its head weight in
        # f32 through the loss kernels (2x HBM traffic; profiled r3)
        p_out = params[out_name]
        cdtype = self.compute_dtype
        if cdtype != self.param_dtype:
            p_out = tree_cast(p_out, cdtype)
        loss = out_impl.loss(out_conf, p_out, h, labels, train=train,
                             rng=k_out, mask=mask)
        new_state[out_name] = state.get(out_name, {})
        # L1/L2 (reference BaseLayer calcL1/calcL2 summed into score)
        for name, lc in zip(self.layer_names, self.layer_confs):
            loss = loss + l1_l2_penalty(lc, params[name])
        aux, new_state = pop_aux_losses(new_state)
        if train:
            loss = loss + aux
        extras = {"carries": new_carries} if carries is not None else {}
        return loss, (new_state, extras)

    # ------------------------------------------------------------------- fit

    # score_value is lazily materialized: the jitted step returns a DEVICE
    # scalar, and converting it eagerly would force a host sync every
    # iteration (~100ms per batch through a remote-device tunnel). The
    # setter accepts device scalars; the getter pays the sync on first
    # read (listeners that read every iteration opt into that cost).
    @property
    def score_value(self):
        v = getattr(self, "_score_raw", float("nan"))
        if not isinstance(v, float):
            v = float(v)
            self._score_raw = v
        return v

    @score_value.setter
    def score_value(self, v):
        self._score_raw = v

    def _get_train_step(self):
        if self._train_step is None:
            confs = dict(zip(self.layer_names, self.layer_confs))
            axes = getattr(self, "_mesh_axes", None)
            self._train_step = make_train_step(
                self._loss, self.tx, confs, mesh=self._mesh,
                zero1_opt_state=(self.opt_state if self._zero1 else None),
                data_axis=(axes or {}).get("data", "data"),
                param_sharding=getattr(self, "_param_sh", None),
                overlap=getattr(self, "_overlap_plan", None))
        return self._train_step

    def _batch_dict(self, ds: DataSet):
        b = {"features": jnp.asarray(ds.features), "labels": jnp.asarray(ds.labels)}
        if ds.features_mask is not None:
            b["features_mask"] = jnp.asarray(ds.features_mask)
        if ds.labels_mask is not None:
            b["labels_mask"] = jnp.asarray(ds.labels_mask)
        return self._globalize_batch(b)

    def _globalize_batch(self, b):
        """Process-spanning mesh: this process's batch is its LOCAL shard
        of the global batch — assemble the global arrays (see
        distributed/global_mesh.py). Single-process meshes pass through
        (the jitted step's in_shardings place the batch)."""
        if not getattr(self, "_multiprocess", False):
            return b
        from deeplearning4j_tpu.distributed.global_mesh import globalize_batch

        axes = getattr(self, "_mesh_axes", None)
        return globalize_batch(b, self._mesh,
                               (axes or {}).get("data", "data"))

    def fit_scanned(self, data, labels=None, epochs: int = 1):
        """Whole-epoch fused training: every minibatch is staged on device
        and each epoch runs as ONE jitted lax.scan dispatch (the fit-path
        MFU mode — BASELINE's "end-to-end MFU via fit()"). Identical
        training math to fit() for plain SGD-family runs on uniform
        batches (rng streams differ, which only matters under dropout);
        unsupported config modes (solvers, TBPTT, pretraining,
        iterations>1) raise instead of silently diverging. Listeners fire
        once per epoch with the epoch-mean score. The staged batches must
        fit in device memory; fit() remains the streaming path.
        """
        from deeplearning4j_tpu.nn.training import fused_fit

        if self.params is None:
            self.init()
        if labels is not None:
            data = DataSet(data, labels)
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])
        return fused_fit(self, [self._batch_dict(ds) for ds in data], epochs)

    def resume_from(self, checkpoint_dir: str, step=None, *,
                    target_mesh=None, target_axes=None):
        """Elastic-recovery resume entry: restore params / optimizer
        state / step counter from an Orbax checkpoint directory
        (`util/orbax_checkpoint.ShardedCheckpointer` layout) INTO this
        net, keeping its runtime configuration (mesh, listeners).
        Returns the restored step (0 when the directory has no
        checkpoint yet: a cold start, not an error).

        target_mesh/target_axes route the restore through the portable
        resharding engine (`reshard/`): the checkpoint may have been
        written under ANY mesh shape / axis roles / process count, and
        each process reads only the shard slices its target placement
        needs. Without a target mesh, call before `set_mesh` when
        rejoining a re-formed fleet — the restored host values ride
        jit's replicated placement on the next `fit`."""
        from deeplearning4j_tpu.util.orbax_checkpoint import (
            ShardedCheckpointer,
        )

        try:
            ShardedCheckpointer(checkpoint_dir).restore(
                self, step=step, target_mesh=target_mesh,
                target_axes=target_axes)
        except FileNotFoundError:
            if step is not None:  # a NAMED step missing is a real error
                raise
            return 0
        return self.iteration_count

    def fit(self, data, labels=None, epochs: int = 1):
        """Train (reference fit(DataSetIterator):1011). Accepts a
        DataSetIterator, a DataSet, or (features, labels) arrays."""
        if self.params is None:
            self.init()
        if labels is not None:
            data = DataSet(data, labels)
        single_batch = isinstance(data, DataSet)
        if single_batch:
            # nothing to prefetch ahead of one batch: the pipeline's
            # synchronous fallback skips the per-call producer thread
            # (fit_steps — the elastic engine — lands here every step)
            data = ListDataSetIterator([data])
        it = data
        if self.conf.pretrain:
            self.pretrain(it)
            it.reset()
        if not self.conf.backprop:
            return self
        g = self.conf.conf
        if str(g.optimization_algo) != str(
                OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT):
            return self._fit_with_solver(it, epochs)
        step = self._get_train_step()
        tbptt_on = self.conf.backprop_type in (BackpropType.TRUNCATED_BPTT,
                                               "truncated_bptt")

        def convert(ds):
            # runs on the input-pipeline prefetch thread: host->device
            # conversion + process-spanning globalization overlap step
            # compute (data/pipeline.py). None = a TBPTT sequence, which
            # converts per truncation window on the step thread instead.
            if (tbptt_on and np.asarray(ds.features).ndim == 3
                    and ds.features.shape[1] > self.conf.tbptt_fwd_length):
                return None
            return self._batch_dict(ds)

        from deeplearning4j_tpu.data.pipeline import iter_prefetched
        from deeplearning4j_tpu.telemetry import get_default as _telemetry
        from deeplearning4j_tpu.telemetry.memstat import sampler_for_net

        # batch-boundary memory sampling: one modulo per iteration unless
        # DL4J_TPU_MEM_EVERY enables the cadence (memstat.on_step)
        mem = sampler_for_net(self, _telemetry())

        for _ in range(epochs):
            it.reset()
            for ds, batch in iter_prefetched(
                    it, convert, depth=0 if single_batch else None):
                if batch is None:
                    self._fit_tbptt(ds, step)
                    continue
                # reference runs `iterations` optimizer passes per minibatch
                # (StochasticGradientDescent.java:55)
                for _i in range(max(1, g.iterations)):
                    self.params, self.opt_state, self.state, loss, _ = step(
                        self.params, self.opt_state, self.state,
                        self._next_rng(), batch)
                    self.score_value = loss
                    self.iteration_count += 1
                    for lst in self.listeners:
                        lst.iteration_done(self, self.iteration_count)
                    mem.on_step(self.iteration_count)
            self.epoch_count += 1
        return self

    def _fit_with_solver(self, it, epochs: int):
        """Second-order / line-search training path (reference Solver.java
        dispatch on OptimizationAlgorithm — CG/LBFGS/line-GD run multiple
        line-searched passes per minibatch instead of the fused SGD step)."""
        from deeplearning4j_tpu.optimize.solvers import Solver

        tbptt = self.conf.backprop_type in (BackpropType.TRUNCATED_BPTT,
                                            "truncated_bptt")
        solver = Solver(self)

        def convert(ds):
            # mirror the SGD path's condition: TBPTT only engages for
            # 3-D sequences longer than the truncation window (the
            # pipeline re-raises this on the step thread)
            if (tbptt and np.asarray(ds.features).ndim == 3
                    and ds.features.shape[1] > self.conf.tbptt_fwd_length):
                raise ValueError(
                    "TRUNCATED_BPTT requires "
                    "STOCHASTIC_GRADIENT_DESCENT; second-order solvers "
                    "would differentiate the full sequence")
            return self._batch_dict(ds)

        from deeplearning4j_tpu.data.pipeline import iter_prefetched

        for _ in range(epochs):
            it.reset()
            for _ds, batch in iter_prefetched(it, convert):
                solver.optimize(batch, rng=self._next_rng())
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration_count)
            self.epoch_count += 1
        return self

    def _initial_carries(self, batch_size):
        """Zero carries for every recurrent layer (keyed by layer name)."""
        carries = {}
        for name, lc, impl in zip(self.layer_names, self.layer_confs, self.impls):
            if isinstance(lc, BaseRecurrentLayer) and hasattr(impl, "initial_carry"):
                carries[name] = impl.initial_carry(lc, batch_size, self.compute_dtype)
        return carries

    def _fit_tbptt(self, ds: DataSet, step):
        """Truncated BPTT (reference doTruncatedBPTT): slide a window of
        tbptt_fwd_length over time. RNN carries flow between segments
        (threaded through the jitted step as batch inputs/extras) but
        gradients do not — each segment is one jitted step, so the gradient
        truncation length equals the forward window (the reference's default
        fwdLen == backLen configuration)."""
        T = ds.features.shape[1]
        L = self.conf.tbptt_fwd_length
        if np.asarray(ds.labels).ndim != 3:
            raise ValueError(
                "TRUNCATED_BPTT needs time-distributed labels "
                f"[batch, time, n_out]; got shape {np.asarray(ds.labels).shape}. "
                "A per-sequence label would be counted once per segment "
                "against mid-sequence activations — train with standard BPTT "
                "instead")
        carries = self._initial_carries(ds.features.shape[0])
        for t0 in range(0, T, L):
            sub = DataSet(
                ds.features[:, t0:t0 + L],
                ds.labels[:, t0:t0 + L],
                None if ds.features_mask is None else ds.features_mask[:, t0:t0 + L],
                None if ds.labels_mask is None else ds.labels_mask[:, t0:t0 + L],
            )
            batch = self._batch_dict(sub)
            batch["carries"] = carries
            self.params, self.opt_state, self.state, loss, extras = step(
                self.params, self.opt_state, self.state, self._next_rng(), batch)
            carries = extras.get("carries", carries)
            self.score_value = loss
            self.iteration_count += 1
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration_count)

    # -------------------------------------------------------------- pretrain
    def pretrain(self, it, epochs: int = 1):
        """Greedy layer-wise pretraining (reference pretrain:165): for each
        pretrain layer (RBM/AutoEncoder), train on the activations of the
        stack below it."""
        if self.params is None:
            self.init()
        if isinstance(it, DataSet):
            it = ListDataSetIterator([it])
        for i, (name, lc, impl) in enumerate(
                zip(self.layer_names, self.layer_confs, self.impls)):
            if not lc.is_pretrain_layer():
                continue
            tx = build_optimizer(self.conf.conf, {name: lc})
            # the optimizer's per-layer lr/updater overrides key on layer
            # names, so feed it {name: params} — not the bare inner dict
            opt = tx.init({name: self.params[name]})

            @jax.jit
            def pstep(p, opt_state, rng, x, _impl=impl, _lc=lc, _tx=tx,
                      _name=name):
                loss, grads = jax.value_and_grad(
                    lambda q: _impl.pretrain_loss(_lc, q[_name], x, rng))(
                        {_name: p})
                updates, opt_state = _tx.update(grads, opt_state, {_name: p})
                return (optax.apply_updates({_name: p}, updates)[_name],
                        opt_state, loss)

            featurize = None
            if i > 0:
                # one compile per LAYER (to_layer=i is baked into the
                # traced program), reused across the whole epoch loop
                featurize = jax.jit(  # graftlint: disable=G005
                    lambda p, s, x: self._forward(p, s, x, train=False, rng=None,
                                                  to_layer=i)[0])
            for _ in range(epochs):
                it.reset()
                while it.has_next():
                    ds = it.next()
                    x = jnp.asarray(ds.features, self.compute_dtype)
                    if featurize is not None:
                        x = featurize(self.params, self.state, x)
                    p_new, opt, loss = pstep(self.params[name], opt, self._next_rng(), x)
                    self.params = dict(self.params, **{name: p_new})
                    self.score_value = loss
        return self

    # ------------------------------------------------------------- inference
    def feed_forward(self, x, train: bool = False):
        """All layer activations (reference feedForward:614)."""
        acts, _, _ = self._forward(self.params, self.state, jnp.asarray(x),
                                   train=train, rng=self._next_rng() if train else None,
                                   collect=True)
        return acts

    def output(self, x, train: bool = False, mask=None):
        """Network output (reference output:1500-1582). With a mesh set,
        inference shards the batch over the 'data' axis — the distributed-
        evaluation path (reference EvaluateFlatMapFunction + merge)."""
        axes = getattr(self, "_mesh_axes", None)
        data_axis = (axes or {}).get("data", "data")
        has_data = (self._mesh is not None
                    and data_axis in self._mesh.axis_names)
        if self._output_jit is None:
            def _out(params, state, x, mask):
                y, _, _ = self._forward(params, state, x, train=False, rng=None,
                                        mask=mask)
                return y
            if has_data:
                from deeplearning4j_tpu.nn.training import mesh_shardings

                repl, data = mesh_shardings(self._mesh, data_axis)
                p_in = (None if getattr(self, "_param_sh", None) is not None
                        else repl)
                # process-spanning mesh: the result must come back fully
                # replicated (a data-sharded output spans non-addressable
                # devices and cannot be fetched host-side)
                out_sh = (repl if getattr(self, "_multiprocess", False)
                          else data)
                self._output_jit = jax.jit(
                    _out, in_shardings=(p_in, repl, data, None),
                    out_shardings=out_sh)
            else:
                self._output_jit = jax.jit(_out)
        if train:
            y, _, _ = self._forward(self.params, self.state, jnp.asarray(x),
                                    train=True, rng=self._next_rng(), mask=mask)
            return y
        x = jnp.asarray(x)
        if has_data:
            # sharded inference needs batch % mesh == 0: pad-and-slice
            # (EvaluateFlatMapFunction handles uneven shards semantically)
            from deeplearning4j_tpu.nn.training import pad_batch_to_multiple

            B = x.shape[0]
            bundle = (x,) if mask is None else (x, mask)
            bundle, pad = pad_batch_to_multiple(bundle,
                                                self._mesh.shape[data_axis])
            x = bundle[0]
            mask = bundle[1] if mask is not None else None
            if getattr(self, "_multiprocess", False):
                # inference takes the FULL batch on every process (unlike
                # fit's per-process shards): globalize it data-sharded
                from deeplearning4j_tpu.distributed.global_mesh import (
                    globalize_full,
                )

                x = globalize_full(x, self._mesh, data_axis)
                if mask is not None:
                    mask = globalize_full(mask, self._mesh, data_axis)
            if pad:
                return self._output_jit(self.params, self.state, x, mask)[:B]
        return self._output_jit(self.params, self.state, x, mask)

    def predict(self, x):
        """Class indices (reference predict)."""
        return np.asarray(jnp.argmax(self.output(x), axis=-1))

    def inference_fn(self):
        """A pure ``(params, state, x, mask=None) -> y`` inference-mode
        forward for external jit owners — the serving engine
        (serving/engine.py) wraps this per replica so IT controls the
        compile cache (one trace per padding bucket, zero retraces after
        warmup), which `output()`'s internal jit cannot promise. No rng,
        no state mutation: inference forwards are row-independent, the
        property the serving padding proof relies on."""
        def fwd(params, state, x, mask=None):
            y, _, _ = self._forward(params, state, x, train=False,
                                    rng=None, mask=mask)
            return y
        return fwd

    def incremental_decode_fn(self, kv_dtype: str = "f32",
                              page_size: int = 16):
        """A pure jitted-step body ``(params, state, cache, token, pos)
        -> (probs, cache)`` — autoregressive decode with the KV cache as
        explicit threaded state (nn/decode.py; same contract as
        ComputationGraph.incremental_decode_fn). This is the
        productionized rnnTimeStep:2147 for attention stacks, which
        `rnn_time_step` rejects as unable to stream causally.
        kv_dtype="int8" reads/writes the quantized paged cache."""
        from deeplearning4j_tpu.nn.decode import make_decode_fn

        return make_decode_fn(self, kv_dtype, page_size)

    def prefill_fn(self, kv_dtype: str = "f32", page_size: int = 16):
        """The chunked-prefill twin of `incremental_decode_fn`:
        ``(params, state, cache, tokens, kmask, rows, start, last_idx)
        -> (probs_last, cache)`` — see nn/decode.make_prefill_fn."""
        from deeplearning4j_tpu.nn.decode import make_prefill_fn

        return make_prefill_fn(self, kv_dtype, page_size)

    def verify_decode_fn(self, kv_dtype: str = "f32",
                         page_size: int = 16):
        """The speculative verification step ``(params, state, cache,
        tokens [B, K], pos) -> (probs [B, K, V], cache)`` — K candidate
        tokens per row checked in ONE fixed-shape call
        (nn/decode.make_verify_fn)."""
        from deeplearning4j_tpu.nn.decode import make_verify_fn

        return make_verify_fn(self, kv_dtype, page_size)

    def init_kv_cache(self, batch: int, capacity: int,
                      kv_dtype: str = "f32", page_size: int = 16):
        """Zeroed decode cache for `batch` rows of `capacity` key slots
        (nn/decode.init_cache)."""
        from deeplearning4j_tpu.nn.decode import init_cache

        return init_cache(self, batch, capacity, kv_dtype, page_size)

    def score(self, dataset: DataSet = None, training: bool = False):
        """Loss on a dataset (reference score()). training=False uses
        inference-mode forward (BatchNorm running stats, no dropout)."""
        if dataset is None:
            return self.score_value
        batch = self._batch_dict(dataset)
        loss, _ = self._loss(self.params, self.state, None, batch, train=training)
        return float(loss)

    def score_examples(self, dataset, add_regularization: bool = False):
        """One score PER EXAMPLE [batch] — the ranking/anomaly-scoring API
        (reference spark ScoreExamplesFunction / scoreExamples:1969).
        Inference-mode forward; `add_regularization` adds the network's
        L1/L2 penalty to every example's score like the reference's
        addRegularizationTerms. With a mesh set, the batch shards over the
        'data' axis like output()."""
        batch = self._batch_dict(dataset)
        key = bool(add_regularization)
        if key not in self._score_examples_jit:
            def _scores(params, state, batch):
                x = batch["features"]
                fmask = batch.get("features_mask")
                lmask = batch.get("labels_mask")
                out_conf = self.layer_confs[-1]
                if not isinstance(out_conf, BaseOutputLayer):
                    raise ValueError(
                        "Last layer must be an OutputLayer to score")
                n = len(self.layer_confs)
                h, _, _ = self._forward(params, state, x, train=False,
                                        rng=None, mask=fmask,
                                        to_layer=n - 1)
                proc = self.conf.get_preprocessor(n - 1)
                if proc is not None:
                    h = proc.pre_process(h)
                mask = lmask if lmask is not None else (
                    fmask if isinstance(out_conf, RnnOutputLayer) else None)
                p_out = params[self.layer_names[-1]]
                if self.compute_dtype != self.param_dtype:
                    p_out = tree_cast(p_out, self.compute_dtype)
                per = self.impls[-1].loss(
                    out_conf, p_out, h, batch["labels"], train=False,
                    rng=None, mask=mask, per_example=True)
                if add_regularization:
                    reg = 0.0
                    for name, lc in zip(self.layer_names, self.layer_confs):
                        reg = reg + l1_l2_penalty(lc, params[name])
                    per = per + reg
                return per

            axes = getattr(self, "_mesh_axes", None)
            data_axis = (axes or {}).get("data", "data")
            if (self._mesh is not None
                    and data_axis in self._mesh.axis_names):
                from deeplearning4j_tpu.nn.training import mesh_shardings

                repl, data = mesh_shardings(self._mesh, data_axis)
                p_in = (None if getattr(self, "_param_sh", None) is not None
                        else repl)
                batch_sh = jax.tree.map(lambda _: data, batch)
                self._score_examples_jit[key] = jax.jit(
                    _scores, in_shardings=(p_in, repl, batch_sh),
                    out_shardings=data)
            else:
                self._score_examples_jit[key] = jax.jit(_scores)
        axes = getattr(self, "_mesh_axes", None)
        data_axis = (axes or {}).get("data", "data")
        if self._mesh is not None and data_axis in self._mesh.axis_names:
            from deeplearning4j_tpu.nn.training import pad_batch_to_multiple

            B = np.asarray(dataset.features).shape[0]
            batch, pad = pad_batch_to_multiple(
                batch, self._mesh.shape[data_axis])
            per = self._score_examples_jit[key](self.params, self.state,
                                                batch)
            return np.asarray(per)[:B]
        return np.asarray(
            self._score_examples_jit[key](self.params, self.state, batch))

    def evaluate(self, it, top_n: int = 1):
        """Classification evaluation (reference evaluate:2311); top_n > 1
        additionally tracks top-N accuracy (Evaluation.topNAccuracy)."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        ev = Evaluation(top_n=top_n)
        if isinstance(it, DataSet):
            it = ListDataSetIterator([it])
        it.reset()
        while it.has_next():
            ds = it.next()
            out = self.output(ds.features)
            ev.eval(ds.labels, np.asarray(out), mask=ds.labels_mask)
        from deeplearning4j_tpu.telemetry import get_default as _telemetry

        _telemetry().eval(ev, top_n=top_n)  # no-op unless telemetry is on
        return ev

    # ------------------------------------------------- streaming RNN inference
    def rnn_clear_previous_state(self):
        self._rnn_carries = None

    def rnn_time_step(self, x):
        """Stateful single/multi-step inference (reference rnnTimeStep:2147).
        x: [batch, n_in] (one step) or [batch, time, n_in]. Raises for layers
        that cannot stream causally (bidirectional LSTM, self-attention —
        the reference throws UnsupportedOperationException)."""
        for name, lc, impl in zip(self.layer_names, self.layer_confs, self.impls):
            if isinstance(lc, BaseRecurrentLayer) and not hasattr(
                    impl, "initial_carry"):
                raise ValueError(
                    f"rnn_time_step: layer '{name}' ({type(lc).__name__}) "
                    "cannot stream causally — it needs the full sequence "
                    "(reference throws UnsupportedOperationException)")
        x = jnp.asarray(x, self.compute_dtype)
        single = x.ndim == 2
        if single:
            x = x[:, None, :]
        carries = self._rnn_carries
        if carries is None:
            carries = self._initial_carries(x.shape[0])
        if self._rnn_jit is None:
            def _step(params, state, x, carries):
                return self._forward(params, state, x, train=False, rng=None,
                                     carries=carries)
            self._rnn_jit = jax.jit(_step)
        y, _, new_carries = self._rnn_jit(self.params, self.state, x, carries)
        self._rnn_carries = {**carries, **new_carries}
        return y[:, -1, :] if single and y.ndim == 3 else y

    def rnn_activate_using_stored_state(self, x, *, training: bool = False,
                                        store_last_for_tbptt: bool = False):
        """Full-sequence activations starting from the STORED streaming
        state (reference rnnActivateUsingStoredState,
        MultiLayerNetwork.java:2203): unlike feed_forward, recurrent layers
        resume from the rnn_time_step/TBPTT state map instead of zeros;
        unlike rnn_time_step, the stored state is NOT advanced unless
        store_last_for_tbptt=True. Returns the list of layer activations
        (one per layer, like feed_forward)."""
        x = jnp.asarray(x, self.compute_dtype)
        if x.ndim != 3:
            raise ValueError("rnn_activate_using_stored_state expects "
                             f"[batch, time, n_in]; got {x.shape}")
        carries = self._rnn_carries
        if carries is None:
            carries = self._initial_carries(x.shape[0])
        acts, _, new_carries = self._forward(
            self.params, self.state, x,
            train=training, rng=self._next_rng() if training else None,
            carries=carries, collect=True)
        if store_last_for_tbptt:
            self._rnn_carries = {**carries, **new_carries}
        return acts

    # -------------------------------------------------------- params plumbing
    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.params))

    def params_flat(self) -> np.ndarray:
        """Flat parameter vector (reference params():deterministic layer order)
        for averaging/serialization parity."""
        leaves = jax.tree.leaves(self.params)
        return np.concatenate([np.asarray(l).ravel() for l in leaves]) if leaves else np.zeros(0)

    def set_params_flat(self, flat: np.ndarray):
        leaves, treedef = jax.tree.flatten(self.params)
        out, off = [], 0
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(jnp.asarray(flat[off:off + n], l.dtype).reshape(l.shape))
            off += n
        self.params = jax.tree.unflatten(treedef, out)

    def clone(self) -> "MultiLayerNetwork":
        net = MultiLayerNetwork(copy.deepcopy(self.conf))
        net.init()
        if self.params is not None:
            net.params = jax.tree.map(jnp.copy, self.params)
            net.state = jax.tree.map(jnp.copy, self.state)
            net.opt_state = self.opt_state
        return net
