"""Weight initialization schemes (reference nn/weights/WeightInit.java +
WeightInitUtil.java: DISTRIBUTION, NORMALIZED, SIZE, UNIFORM, VI, ZERO,
XAVIER, RELU)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.enums import WeightInit


def init_weights(rng, shape, scheme, dist=None, dtype=jnp.float32,
                 fan_in=None, fan_out=None):
    """Sample a weight array per the named scheme.

    fan_in/fan_out default to shape[0]/shape[-1] (dense convention); conv
    layers pass receptive-field-scaled fans explicitly.
    """
    shape = tuple(shape)
    if fan_in is None:
        fan_in = shape[0] if len(shape) > 1 else shape[0]
    if fan_out is None:
        fan_out = shape[-1]
    s = scheme if isinstance(scheme, str) else scheme.value
    s = s.lower()
    if s == WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if s == WeightInit.DISTRIBUTION:
        if dist is None:
            raise ValueError("WeightInit.DISTRIBUTION requires a Distribution")
        return dist.sample(rng, shape, dtype)
    if s == WeightInit.XAVIER:
        # Glorot normal: N(0, 2/(fan_in+fan_out)) — reference WeightInitUtil
        std = jnp.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(rng, shape, dtype)
    if s == WeightInit.RELU:
        # He normal: N(0, 2/fan_in)
        std = jnp.sqrt(2.0 / fan_in)
        return std * jax.random.normal(rng, shape, dtype)
    if s == WeightInit.LECUN:
        std = jnp.sqrt(1.0 / fan_in)
        return std * jax.random.normal(rng, shape, dtype)
    if s == WeightInit.UNIFORM:
        a = 1.0 / jnp.sqrt(jnp.asarray(float(fan_in)))
        return jax.random.uniform(rng, shape, dtype, minval=-a, maxval=a)
    if s == WeightInit.VI:
        # reference "variance init": uniform scaled by fan sum
        r = jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, minval=-r, maxval=r)
    if s == WeightInit.SIZE:
        r = jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, minval=-r, maxval=r)
    if s == WeightInit.NORMALIZED:
        u = jax.random.uniform(rng, shape, dtype) - 0.5
        return u / jnp.asarray(float(fan_in), dtype)
    raise ValueError(f"Unknown weight init '{scheme}'")
