"""Updaters — SGD-family update rules as pure gradient transforms.

Reference: nn/updater/* (BaseUpdater per-param state map, SgdUpdater,
AdamUpdater, AdaGradUpdater, AdaDeltaUpdater, NesterovsUpdater,
RmsPropUpdater, NoOpUpdater; lr/momentum schedules and gradient
normalization in BaseUpdater; MultiLayerUpdater composes per-layer).

TPU-native: each updater is an optax GradientTransformation; per-layer
overrides (learning rate / updater choice — reference's per-layer config
inheritance) compose via optax.multi_transform keyed on the layer name.
Updater state is a pytree that lives in the jitted train step (donated),
checkpoints with the model (reference ModelSerializer stores the updater),
and never needs cross-worker merging — under data parallelism it is
identically replicated, which subsumes the reference's UpdaterAggregator.

Gradient normalization (reference GradientNormalization enum) is applied to
the per-layer gradient pytree before the update transform.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from deeplearning4j_tpu.nn.conf.enums import GradientNormalization, LearningRatePolicy, Updater


def make_schedule(conf, layer_lr=None):
    """Learning-rate schedule per the reference's LearningRatePolicy."""
    base = layer_lr if layer_lr is not None else conf.learning_rate
    policy = conf.lr_policy
    if conf.lr_schedule:
        # explicit {iteration: lr} map (reference learningRateSchedule)
        pairs = sorted((int(k), float(v)) for k, v in conf.lr_schedule.items())

        def sched(step):
            lr = jnp.asarray(base, jnp.float32)
            for it, v in pairs:
                lr = jnp.where(step >= it, v, lr)
            return lr

        return sched
    if policy in (LearningRatePolicy.NONE, "none", None):
        return lambda step: base
    if policy == LearningRatePolicy.EXPONENTIAL:
        return lambda step: base * conf.lr_policy_decay_rate ** step
    if policy == LearningRatePolicy.INVERSE:
        return lambda step: base / (1.0 + conf.lr_policy_decay_rate * step) ** conf.lr_policy_power
    if policy == LearningRatePolicy.POLY:
        steps = max(conf.decay_steps, 1)
        return lambda step: base * jnp.maximum(0.0, 1.0 - step / steps) ** conf.lr_policy_power
    if policy == LearningRatePolicy.SIGMOID:
        return lambda step: base / (
            1.0 + jnp.exp(-conf.lr_policy_decay_rate * (step - conf.lr_policy_steps))
        )
    if policy == LearningRatePolicy.STEP:
        return lambda step: base * conf.lr_policy_decay_rate ** jnp.floor(
            step / conf.lr_policy_steps
        )
    if policy == LearningRatePolicy.TORCH_STEP:
        return lambda step: base * conf.lr_policy_decay_rate ** jnp.floor(
            step / jnp.maximum(conf.lr_policy_steps, 1.0)
        )
    if policy == LearningRatePolicy.COSINE:
        steps = max(conf.decay_steps, 1)
        return optax.cosine_decay_schedule(base, steps)
    if policy == LearningRatePolicy.WARMUP_COSINE:
        steps = max(conf.decay_steps, 1)
        return optax.warmup_cosine_decay_schedule(
            0.0, base, max(conf.warmup_steps, 1), steps
        )
    raise ValueError(f"Unknown lr policy {policy}")


def _single_transform(conf, updater, lr_sched):
    u = (updater or Updater.SGD)
    u = u.value if hasattr(u, "value") else u
    if u == Updater.SGD:
        return optax.sgd(lr_sched)
    if u == Updater.NESTEROVS:
        return optax.sgd(lr_sched, momentum=conf.momentum, nesterov=True)
    if u == Updater.ADAM:
        return optax.adam(lr_sched, b1=conf.adam_mean_decay, b2=conf.adam_var_decay,
                          eps=conf.epsilon)
    if u == Updater.ADAMW:
        return optax.adamw(lr_sched, b1=conf.adam_mean_decay, b2=conf.adam_var_decay,
                           eps=conf.epsilon, weight_decay=conf.weight_decay or 1e-4)
    if u == Updater.ADADELTA:
        return optax.adadelta(learning_rate=1.0, rho=conf.rho, eps=conf.epsilon)
    if u == Updater.ADAGRAD:
        return optax.adagrad(lr_sched, eps=conf.epsilon)
    if u == Updater.RMSPROP:
        return optax.rmsprop(lr_sched, decay=conf.rms_decay, eps=conf.epsilon)
    if u == Updater.LION:
        return optax.lion(lr_sched)
    if u == Updater.LAMB:
        return optax.lamb(lr_sched)
    if u == Updater.NONE:
        return optax.sgd(lr_sched)
    raise ValueError(f"Unknown updater '{u}' (custom updaters: pass an "
                     f"optax.GradientTransformation via network.set_optimizer)")


import typing


class FlatViewTransform(typing.NamedTuple):
    """A GradientTransformation running its inner update over ONE
    concatenated f32 vector. The per-leaf moment updates of adam & friends
    compile to dozens of small fusions (~0.9 ms/step at the 13M-param
    transformer bench, r4 trace); over the flat view they are a single
    fused elementwise kernel. Only valid for ELEMENTWISE update rules
    (sgd/momentum/adam/adamw/adagrad/adadelta/rmsprop/lion) — anything
    with per-layer geometry (lamb trust ratios, multi_transform) keeps the
    tree layout. The mesh paths (TP/EP/PP placement, ZeRO-1) rebuild a
    tree-shaped optimizer via build_optimizer(flat=False): a flat state
    cannot carry per-leaf shardings."""

    init: typing.Callable
    update: typing.Callable


_FLAT_OK = {Updater.SGD, Updater.NESTEROVS, Updater.ADAM, Updater.ADAMW,
            Updater.ADADELTA, Updater.ADAGRAD, Updater.RMSPROP,
            Updater.LION, Updater.NONE, None}


# Version of the flat-view vector layout, stored in checkpoint metadata:
# v1 = every leaf row-major; v2 = lane-hostile leaves axis-rotated
# (_lane_hostile below). upgrade_flat_layout migrates v1 vectors.
FLAT_LAYOUT_VERSION = 2


def upgrade_flat_layout(vec, params):
    """Reorder a v1 (all-row-major) flat vector — params, adam moments —
    into the v2 layout, given the param pytree it flattens."""
    outs = []
    off = 0
    for l in jax.tree.leaves(params):
        seg = jax.lax.dynamic_slice_in_dim(vec, off, l.size, 0)
        if _lane_hostile(l):
            seg = jnp.ravel(jnp.moveaxis(seg.reshape(l.shape), -1, 0))
        outs.append(seg)
        off += l.size
    return jnp.concatenate(outs)


def flat_state_size(params) -> int:
    return sum(l.size for l in jax.tree.leaves(params))


def _lane_hostile(l):
    """2D+ leaves whose minor dim is below the 128-lane tile (e.g. an
    [D, n_experts] MoE router). Reshaping the flat f32 vector straight to
    such a shape made XLA relayout the ENTIRE vector into a tiled 2D
    form (2.8 ms/step on the 19M-param MoE flagship, r5 trace); storing
    these leaves axis-rotated (minor dim leading) keeps every reshape-
    from-flat lane-aligned and the fix is a cheap tiny transpose."""
    return l.ndim >= 2 and l.shape[-1] < 128


def _flatten_leaves(tree):
    return jnp.concatenate([
        jnp.ravel(jnp.moveaxis(l, -1, 0) if _lane_hostile(l) else l)
        .astype(jnp.float32)
        for l in jax.tree.leaves(tree)])


def named_layer_confs(net):
    """{layer_name: layer_conf} for either container kind (shared by
    build_optimizer's callers: mesh placement, checkpoint restore)."""
    if hasattr(net, "layer_vertices"):
        return {n: v.layer for n, v in net.layer_vertices.items()}
    return dict(zip(net.layer_names, net.layer_confs))


def _unflatten_into(vec, leaves, treedef):
    """Slice a flat vector back into the pytree whose raveled leaves (in
    jax.tree.leaves order) it concatenates — THE definition of the flat
    layout, shared by the per-step update and the state migration."""
    outs = []
    off = 0
    for l in leaves:
        seg = jax.lax.dynamic_slice_in_dim(vec, off, l.size, 0)
        if _lane_hostile(l):
            rot = (l.shape[-1],) + l.shape[:-1]
            outs.append(jnp.moveaxis(seg.reshape(rot), 0, -1)
                        .astype(l.dtype))
        else:
            outs.append(seg.reshape(l.shape).astype(l.dtype))
        off += l.size
    return jax.tree.unflatten(treedef, outs)


def rebuild_other_layout(net):
    """A GradientTransformation in the OPPOSITE updater-state layout of
    net.tx (per-leaf tree <-> flat view) — the checkpoint layout bridge
    shared by ModelSerializer.restore and the orbax ShardedCheckpointer
    (a checkpoint may hold either layout regardless of the target net's
    default)."""
    was_flat = isinstance(net.tx, FlatViewTransform)
    return build_optimizer(net.conf.conf, named_layer_confs(net),
                           flat=not was_flat)


def unflatten_state_like(flat_state, params):
    """Convert a FlatViewTransform optimizer state into the tree-shaped
    layout of the same update rule: any 1-D f32 moment vector of
    total-param length unflattens into the param pytree. Scalars (step
    counts) pass through."""
    leaves = jax.tree.leaves(params)
    total = sum(l.size for l in leaves)
    treedef = jax.tree.structure(params)

    def conv(x):
        if hasattr(x, "ndim") and x.ndim == 1 and x.size == total:
            return _unflatten_into(x, leaves, treedef)
        return x

    return jax.tree.map(conv, flat_state)


def flatten_transform(inner) -> FlatViewTransform:
    def init(params):
        return inner.init(_flatten_leaves(params))

    def update(grads, state, params=None):
        leaves, treedef = jax.tree.flatten(grads)
        flat_g = _flatten_leaves(grads)
        flat_p = None if params is None else _flatten_leaves(params)
        upd, new_state = inner.update(flat_g, state, flat_p)
        return _unflatten_into(upd, leaves, treedef), new_state

    return FlatViewTransform(init, update)


# Below this many parameters the flat view loses: its fixed concat/slice
# passes outrun the per-leaf fusions they replace (same-window A/B on
# v5e: LeNet@61k params 1.63M img/s flat vs 1.74M tree; the 13M-param
# transformer gains ~0.8 ms/step the other way).
_FLAT_MIN_PARAMS = 1 << 20


def build_optimizer(conf, layer_confs, flat: bool = True, params=None):
    """Build the network optimizer.

    layer_confs: {layer_name: layer_conf}. If no layer overrides
    updater/learning_rate the result is a single transform; otherwise an
    optax.multi_transform keyed by top-level param-tree key (= layer name),
    mirroring the reference's MultiLayerUpdater. `flat` (default) lets an
    elementwise update rule run fused over the flat param view; pass the
    params pytree so small models keep the per-leaf layout (the flat
    view only pays off past _FLAT_MIN_PARAMS elements).
    """
    overrides = {
        name: lc for name, lc in layer_confs.items()
        if (getattr(lc, "updater", None) not in (None, conf.updater))
        or getattr(lc, "learning_rate", None) is not None
    }
    if flat and params is not None:
        flat = sum(l.size for l in jax.tree.leaves(params)) >= _FLAT_MIN_PARAMS
    if not overrides:
        tx = _single_transform(conf, conf.updater, make_schedule(conf))
        u = conf.updater
        if isinstance(u, str):
            try:
                u = Updater(u)
            except ValueError:
                u = None if u == "" else u
        if flat and u in _FLAT_OK:
            return flatten_transform(tx)
        return tx

    transforms = {"__default__": _single_transform(conf, conf.updater, make_schedule(conf))}
    labels = {}
    for name, lc in layer_confs.items():
        if name in overrides:
            sched = make_schedule(conf, layer_lr=getattr(lc, "learning_rate", None))
            transforms[name] = _single_transform(conf, getattr(lc, "updater", None)
                                                 or conf.updater, sched)
            labels[name] = name
        else:
            labels[name] = "__default__"

    def label_fn(params):
        return {k: labels.get(k, "__default__") for k in params}

    return optax.multi_transform(transforms, label_fn)


def normalize_gradients(grads, layer_confs):
    """Apply per-layer gradient normalization (reference BaseUpdater
    preApply / GradientNormalization.java). grads: {layer_name: {param: g}}."""

    def _norm(tree):
        leaves = jax.tree_util.tree_leaves(tree)
        return jnp.sqrt(sum(jnp.sum(l.astype(jnp.float32) ** 2) for l in leaves) + 1e-20)

    out = {}
    for name, g in grads.items():
        lc = layer_confs.get(name)
        gn = getattr(lc, "gradient_normalization", None) if lc else None
        thr = getattr(lc, "gradient_normalization_threshold", 1.0) if lc else 1.0
        if gn in (None, GradientNormalization.NONE, "none"):
            out[name] = g
        elif gn == GradientNormalization.RENORMALIZE_L2_PER_LAYER:
            n = _norm(g)
            out[name] = jax.tree.map(lambda x: x / n, g)
        elif gn == GradientNormalization.RENORMALIZE_L2_PER_PARAM_TYPE:
            out[name] = jax.tree.map(lambda x: x / _norm(x), g)
        elif gn == GradientNormalization.CLIP_ELEMENTWISE_ABSOLUTE_VALUE:
            out[name] = jax.tree.map(lambda x: jnp.clip(x, -thr, thr), g)
        elif gn == GradientNormalization.CLIP_L2_PER_LAYER:
            n = _norm(g)
            scale = jnp.minimum(1.0, thr / n)
            out[name] = jax.tree.map(lambda x: x * scale, g)
        elif gn == GradientNormalization.CLIP_L2_PER_PARAM_TYPE:
            out[name] = jax.tree.map(lambda x: x * jnp.minimum(1.0, thr / _norm(x)), g)
        else:
            raise ValueError(f"Unknown gradient normalization {gn}")
    return out
