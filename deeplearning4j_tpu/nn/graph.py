"""ComputationGraph — the DAG container for multi-input/multi-output nets.

Reference: nn/graph/ComputationGraph.java (~2,500 LoC): topological
sort:235,458-483, init:219-231, fit:545-672, forward over topo order:886,
backprop:958-977; vertex impls under graph/vertex/impl/*.

TPU-native: the topo-order forward IS the traced jaxpr (SURVEY.md §3.2);
vertices are pure functions; backward is jax.grad of the summed output
losses; the whole step is one jitted donated computation.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.datasets.api import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.graph_conf import (
    ComputationGraphConfiguration,
    DuplicateToTimeSeriesVertexConf,
    ElementWiseVertexConf,
    LastTimeStepVertexConf,
    LayerVertexConf,
    MergeVertexConf,
    PreprocessorVertexConf,
    ScaleVertexConf,
    StackVertexConf,
    SubsetVertexConf,
    UnstackVertexConf,
)
from deeplearning4j_tpu.nn.conf.enums import BackpropType, OptimizationAlgorithm
from deeplearning4j_tpu.nn.conf.layers import (
    BaseOutputLayer,
    BaseRecurrentLayer,
    validate_layer_names,
)
from deeplearning4j_tpu.nn.layers import get_impl, l1_l2_penalty
from deeplearning4j_tpu.nn.layers.base import pop_aux_losses
from deeplearning4j_tpu.nn.training import make_train_step, tree_cast
from deeplearning4j_tpu.nn.updater import build_optimizer

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float64": jnp.float64}


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.topo = conf.topological_order()
        self.layer_vertices = {
            name: v for name, v in conf.vertices.items() if isinstance(v, LayerVertexConf)
        }
        self.impls = {name: get_impl(v.layer) for name, v in self.layer_vertices.items()}
        self.output_layer_names = [
            n for n in conf.network_outputs
            if n in self.layer_vertices
            and isinstance(self.layer_vertices[n].layer, BaseOutputLayer)
        ]
        self.params = None
        self.state = None
        self.opt_state = None
        self.tx = None
        self.listeners = []
        self.iteration_count = 0
        self.score_value = float("nan")
        self._train_step = None
        self._scan_fit = None
        self._output_jit = None
        self._score_examples_jit = {}
        self._rng = None
        self._mesh = None
        self._zero1 = False
        self._multiprocess = False
        self._rnn_carries = None  # streaming inference state (rnn_time_step)
        self._rnn_jit = None

    @property
    def compute_dtype(self):
        return _DTYPES[self.conf.conf.dtype]

    @property
    def param_dtype(self):
        return _DTYPES[self.conf.conf.param_dtype]

    def init(self, seed: Optional[int] = None):
        g = self.conf.conf
        key = jax.random.PRNGKey(g.seed if seed is None else seed)
        self._rng = jax.random.fold_in(key, 1)
        params, state = {}, {}
        names = sorted(self.layer_vertices)
        keys = jax.random.split(key, max(len(names), 1))
        for name, k in zip(names, keys):
            v = self.layer_vertices[name]
            validate_layer_names(v.layer)
            p, s = self.impls[name].init(v.layer, k, self.param_dtype)
            params[name] = p
            state[name] = s
        self.params = params
        self.state = state
        self.tx = build_optimizer(
            g, {n: v.layer for n, v in self.layer_vertices.items()},
            params=params)
        self.opt_state = self.tx.init(params)
        return self

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)

    def set_mesh(self, mesh, zero1: bool = False, axes=None,
                 n_microbatches=None, tp_rules=None, overlap=None):
        """Single distributed entry point: axes maps parallelism roles
        ("data"/"model"/"pipe"/"expert") to mesh axis names — see
        parallel/placement.py. Without axes: round-1 pure DP over 'data'.
        overlap: True / bucket bytes / a BucketPlan — bucketed gradient
        allreduce with compute/communication overlap (parallel/overlap.py;
        pure DP only, composes with zero1)."""
        from deeplearning4j_tpu.parallel.placement import configure_mesh

        return configure_mesh(self, mesh, zero1=zero1, axes=axes,
                              n_microbatches=n_microbatches,
                              tp_rules=tp_rules, overlap=overlap)

    def _canonical_params(self):
        """Params in the per-layer layout regardless of an active pipeline
        restructure (read paths: output/score/serialization/flat views)."""
        if getattr(self, "_pp_plan", None) is not None:
            return self._pp_plan.to_canonical(self.params)
        return self.params

    def set_optimizer(self, tx):
        self.tx = tx
        self.opt_state = tx.init(self.params)
        self._train_step = None
        self._scan_fit = None

    def _next_rng(self):
        self._rng, k = jax.random.split(self._rng)
        return k

    # --------------------------------------------------------------- forward
    def _vertex_forward(self, name, vconf, inputs, params, state, train, rng,
                        masks, acts):
        """Non-layer vertex semantics (reference graph/vertex/impl/*)."""
        if isinstance(vconf, MergeVertexConf):
            return jnp.concatenate(inputs, axis=-1)
        if isinstance(vconf, ElementWiseVertexConf):
            op = vconf.op
            out = inputs[0]
            for x in inputs[1:]:
                if op == "add":
                    out = out + x
                elif op == "subtract":
                    out = out - x
                elif op == "product":
                    out = out * x
                elif op == "max":
                    out = jnp.maximum(out, x)
                elif op == "average":
                    out = out + x
                else:
                    raise ValueError(f"elementwise op {op}")
            if op == "average":
                out = out / len(inputs)
            return out
        if isinstance(vconf, SubsetVertexConf):
            return inputs[0][..., vconf.from_idx:vconf.to_idx + 1]
        if isinstance(vconf, PreprocessorVertexConf):
            return vconf.preprocessor.pre_process(inputs[0])
        if isinstance(vconf, ScaleVertexConf):
            return inputs[0] * vconf.scale
        if isinstance(vconf, LastTimeStepVertexConf):
            x = inputs[0]  # [B, T, f]
            mask = masks.get(vconf.mask_input) if vconf.mask_input else None
            if mask is None:
                return x[:, -1, :]
            idx = jnp.maximum(jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0)
            return x[jnp.arange(x.shape[0]), idx, :]
        if isinstance(vconf, DuplicateToTimeSeriesVertexConf):
            ref = acts[vconf.reference_input]
            T = ref.shape[1]
            return jnp.broadcast_to(
                inputs[0][:, None, :], (inputs[0].shape[0], T, inputs[0].shape[1]))
        if isinstance(vconf, StackVertexConf):
            return jnp.concatenate(inputs, axis=0)
        if isinstance(vconf, UnstackVertexConf):
            return jnp.split(inputs[0], vconf.stack_size, axis=0)[vconf.from_idx]
        raise ValueError(f"Unhandled vertex type {type(vconf).__name__} for '{name}'")

    def _time_preserving(self, vconf, T):
        """Whether a vertex maps [B, T, f] -> [B, T, f'] keeping the time
        axis: elementwise/merge/scale vertices by construction; layer
        vertices by their declared InputType mapping (recurrent in ->
        recurrent out of the same length)."""
        if isinstance(vconf, (MergeVertexConf, ElementWiseVertexConf,
                              ScaleVertexConf)):
            return True
        if isinstance(vconf, LayerVertexConf):
            from deeplearning4j_tpu.nn.conf.inputs import InputType

            lc = vconf.layer
            try:
                ot = lc.get_output_type(
                    InputType.recurrent(getattr(lc, "n_in", 0) or 0, T))
            except Exception:
                return False
            return (ot.kind == "recurrent"
                    and ot.timeseries_length == T)
        return False

    def _forward(self, params, state, input_dict, *, train, rng, masks=None,
                 collect=False, carries=None):
        masks = dict(masks) if masks else {}
        acts = {}
        cdtype = self.compute_dtype
        for k, v in input_dict.items():
            v = jnp.asarray(v)
            if jnp.issubdtype(v.dtype, jnp.floating):
                v = v.astype(cdtype)
            acts[k] = v
        new_state = {}
        new_carries = {}
        names = [n for n in self.topo if n not in self.conf.network_inputs]
        rngs = (jax.random.split(rng, max(len(names), 1)) if rng is not None
                else [None] * len(names))
        for name, k in zip(names, rngs):
            vconf = self.conf.vertices[name]
            inputs = [acts[i] for i in self.conf.vertex_inputs[name]]
            if isinstance(vconf, LayerVertexConf):
                x = inputs[0]
                if vconf.preprocessor is not None:
                    x = vconf.preprocessor.pre_process(x)
                p = params.get(name, {})
                if cdtype != self.param_dtype:
                    p = tree_cast(p, cdtype)
                in_mask = masks.get(self.conf.vertex_inputs[name][0])
                want_carry = (carries is not None
                              and isinstance(vconf.layer, BaseRecurrentLayer)
                              and hasattr(self.impls[name], "initial_carry"))
                kw = ({"initial_carry": carries.get(name), "return_carry": True}
                      if want_carry else {})

                def run(p_, s_, x_, _impl=self.impls[name], _lc=vconf.layer,
                        _rng=k, _mask=in_mask, _kw=kw):
                    return _impl.apply(_lc, p_, s_, x_, train=train,
                                       rng=_rng, mask=_mask, **_kw)

                if self.conf.conf.remat:
                    # jax.checkpoint per vertex: activations inside the
                    # vertex are recomputed in the backward instead of
                    # living in HBM for the whole step — the long-context
                    # lever (seq-16k at batch 16 OOMs a 16GB chip without
                    # it; the MultiLayerNetwork container has the same
                    # per-layer policy at multilayer.py:169)
                    run = jax.checkpoint(run)
                out = run(p, state.get(name, {}), x)
                if want_carry:
                    y, s, carry = out
                    new_carries[name] = carry
                else:
                    y, s = out
                acts[name] = y
                new_state[name] = s
            else:
                acts[name] = self._vertex_forward(
                    name, vconf, inputs, params, state, train, k, masks, acts)
            # propagate time masks along the DAG (reference
            # setLayerMaskArrays/feedForwardMaskArrays semantics): a
            # time-preserving vertex carries its first input's mask so
            # downstream recurrent/attention layers see the padding.
            # Gated on vertex SEMANTICS (declared time-preserving kinds /
            # recurrent-output layers), not just output shape — a vertex
            # permuting axes to [B, C, T'] with C == T must not inherit a
            # time mask (ADVICE r3)
            m = masks.get(self.conf.vertex_inputs[name][0])
            y_out = acts[name]
            if (m is not None and hasattr(y_out, "ndim") and y_out.ndim == 3
                    and y_out.shape[0] == m.shape[0]
                    and y_out.shape[1] == m.shape[1]
                    and self._time_preserving(vconf, m.shape[1])):
                masks[name] = m
        for n in self.layer_vertices:
            new_state.setdefault(n, state.get(n, {}))
        if collect:
            return acts, new_state, new_carries
        return [acts[o] for o in self.conf.network_outputs], new_state, new_carries

    def _loss(self, params, state, rng, batch, train=True):
        """Sum of output-layer losses + L1/L2 (reference
        computeGradientAndScore:816)."""
        input_dict = dict(zip(self.conf.network_inputs, batch["features"]))
        masks = {}
        if batch.get("features_masks") is not None:
            masks = {k: m for k, m in zip(self.conf.network_inputs,
                                          batch["features_masks"]) if m is not None}
        n_out = len(self.conf.network_outputs)
        if rng is not None:
            keys = jax.random.split(rng, n_out + 1)
            k_body, k_outs = keys[0], keys[1:]
        else:
            k_body, k_outs = None, [None] * n_out
        acts, new_state, new_carries = self._forward(
            params, state, input_dict, train=train, rng=k_body, masks=masks,
            collect=True, carries=batch.get("carries"))
        loss = 0.0
        labels_list = batch["labels"]
        lmasks = batch.get("labels_masks") or [None] * len(labels_list)
        cdtype = self.compute_dtype
        for out_name, labels, lmask, k_out in zip(
                self.conf.network_outputs, labels_list, lmasks, k_outs):
            vconf = self.conf.vertices[out_name]
            if not isinstance(vconf, LayerVertexConf) or not isinstance(
                    vconf.layer, BaseOutputLayer):
                raise ValueError(f"Output '{out_name}' is not an output layer")
            x = acts[self.conf.vertex_inputs[out_name][0]]
            if vconf.preprocessor is not None:
                x = vconf.preprocessor.pre_process(x)
            # cast output-layer params to the compute dtype like _forward
            # does for every other layer — otherwise a bf16 model streams
            # its [d, V] LM-head weight through the loss kernels in f32
            # (2x the HBM traffic of the declared policy; profiled r3)
            p_out = params[out_name]
            if cdtype != self.param_dtype:
                p_out = tree_cast(p_out, cdtype)
            loss = loss + self.impls[out_name].loss(
                vconf.layer, p_out, x, labels, train=train, rng=k_out,
                mask=lmask)
        for name, v in self.layer_vertices.items():
            loss = loss + l1_l2_penalty(v.layer, params[name])
        aux, new_state = pop_aux_losses(new_state)
        if train:
            loss = loss + aux
        extras = ({"carries": new_carries} if batch.get("carries") is not None
                  else {})
        return loss, (new_state, extras)

    # ------------------------------------------------------------------- fit
    @staticmethod
    def _to_mds(ds):
        if isinstance(ds, MultiDataSet):
            return ds
        return MultiDataSet([ds.features], [ds.labels],
                            None if ds.features_mask is None else [ds.features_mask],
                            None if ds.labels_mask is None else [ds.labels_mask])

    def _batch_dict(self, mds: MultiDataSet):
        b = {
            "features": tuple(jnp.asarray(f) for f in mds.features),
            "labels": tuple(jnp.asarray(l) for l in mds.labels),
        }
        if mds.features_masks is not None:
            b["features_masks"] = tuple(
                None if m is None else jnp.asarray(m) for m in mds.features_masks)
        if mds.labels_masks is not None:
            b["labels_masks"] = tuple(
                None if m is None else jnp.asarray(m) for m in mds.labels_masks)
        return self._globalize_batch(b)

    def _globalize_batch(self, b):
        """Process-spanning mesh: assemble this process's local batch
        shard into global arrays (distributed/global_mesh.py); identity
        on single-process meshes."""
        if not getattr(self, "_multiprocess", False):
            return b
        from deeplearning4j_tpu.distributed.global_mesh import globalize_batch

        axes = getattr(self, "_mesh_axes", None)
        return globalize_batch(b, self._mesh,
                               (axes or {}).get("data", "data"))

    def resume_from(self, checkpoint_dir: str, step=None, *,
                    target_mesh=None, target_axes=None):
        """Elastic-recovery resume entry (same contract as
        `MultiLayerNetwork.resume_from`, including the `reshard/`
        target-mesh routing): restore the latest (or given) Orbax
        checkpoint into this graph, returning the restored step — 0
        when the directory holds no checkpoint yet."""
        from deeplearning4j_tpu.util.orbax_checkpoint import (
            ShardedCheckpointer,
        )

        try:
            ShardedCheckpointer(checkpoint_dir).restore(
                self, step=step, target_mesh=target_mesh,
                target_axes=target_axes)
        except FileNotFoundError:
            if step is not None:  # a NAMED step missing is a real error
                raise
            return 0
        return self.iteration_count

    def fit(self, data, labels=None, epochs: int = 1):
        """Train (reference ComputationGraph.fit:545-672, incl. the
        pretrain:165-equivalent, tbptt branch, and Solver dispatch)."""
        if self.params is None:
            self.init()
        if labels is not None:
            data = DataSet(data, labels)
        single_batch = isinstance(data, (DataSet, MultiDataSet))
        if single_batch:
            # single batch: the pipeline's synchronous fallback skips
            # the per-call producer thread (fit_steps lands here)
            data = ListDataSetIterator([data])
        it = data
        if self.conf.pretrain:
            self.pretrain(it)
            it.reset()
        if not self.conf.backprop:
            return self
        g = self.conf.conf
        if str(g.optimization_algo) != str(
                OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT):
            return self._fit_with_solver(it, epochs)
        self._get_train_step()
        tbptt = self.conf.backprop_type in (BackpropType.TRUNCATED_BPTT,
                                            "truncated_bptt")

        def convert(ds):
            # prefetch-thread work (data/pipeline.py): MultiDataSet
            # coercion + device conversion + globalization overlap the
            # step. batch None = a TBPTT sequence (per-window conversion
            # happens on the step thread).
            mds = self._to_mds(ds)
            if tbptt and self._needs_tbptt(mds):
                return mds, None
            return mds, self._batch_dict(mds)

        from deeplearning4j_tpu.data.pipeline import iter_prefetched
        from deeplearning4j_tpu.telemetry import get_default as _telemetry
        from deeplearning4j_tpu.telemetry.memstat import sampler_for_net

        # batch-boundary memory sampling: one modulo per iteration unless
        # DL4J_TPU_MEM_EVERY enables the cadence (memstat.on_step)
        mem = sampler_for_net(self, _telemetry())

        for _ in range(epochs):
            it.reset()
            for _ds, (mds, batch) in iter_prefetched(
                    it, convert, depth=0 if single_batch else None):
                if batch is None:
                    self._fit_tbptt(mds)
                    continue
                for _i in range(max(1, g.iterations)):
                    self.params, self.opt_state, self.state, loss, _ = self._train_step(
                        self.params, self.opt_state, self.state, self._next_rng(),
                        batch)
                    self.score_value = loss
                    self.iteration_count += 1
                    for lst in self.listeners:
                        lst.iteration_done(self, self.iteration_count)
                    mem.on_step(self.iteration_count)
        return self


    # score_value is lazily materialized: the jitted step returns a DEVICE
    # scalar, and converting it eagerly would force a host sync every
    # iteration (~100ms per batch through a remote-device tunnel). The
    # setter accepts device scalars; the getter pays the sync on first
    # read (listeners that read every iteration opt into that cost).
    @property
    def score_value(self):
        v = getattr(self, "_score_raw", float("nan"))
        if not isinstance(v, float):
            v = float(v)
            self._score_raw = v
        return v

    @score_value.setter
    def score_value(self, v):
        self._score_raw = v

    def _get_train_step(self):
        """Jitted donated train step (same contract as MLN._get_train_step)."""
        if self._train_step is None:
            axes_map = getattr(self, "_mesh_axes", None) or {}
            # seq WITH pipe routes through the PP schedule (its shard_map
            # is manual over the seq axis too); seq alone takes the SP step
            if "seq" in axes_map and "pipe" not in axes_map:
                from deeplearning4j_tpu.parallel.sequence_parallel import (
                    make_sp_train_step,
                )

                sp = make_sp_train_step(self, self._mesh,
                                        seq_axis=axes_map["seq"],
                                        data_axis=axes_map.get("data"))

                def step(params, opt_state, state, rng, batch):
                    masks = list(batch.get("features_masks") or []) + list(
                        batch.get("labels_masks") or [])
                    if any(m is not None for m in masks):
                        raise ValueError(
                            "masks are not supported under sequence "
                            "parallelism — pad to full length")
                    p, o, s, loss = sp(params, opt_state, state, rng,
                                       batch["features"][0],
                                       batch["labels"][0])
                    return p, o, s, loss, {}

                self._train_step = step
            elif getattr(self, "_pp_plan", None) is not None:
                from deeplearning4j_tpu.parallel.pipeline import (
                    make_pp_train_step,
                )

                self._train_step = make_pp_train_step(
                    self, self._pp_plan, self._mesh, self._mesh_axes,
                    self._pp_microbatches, self._resolved_rules)
            else:
                confs = {n: v.layer for n, v in self.layer_vertices.items()}
                axes = getattr(self, "_mesh_axes", None)
                self._train_step = make_train_step(
                    self._loss, self.tx, confs, mesh=self._mesh,
                    zero1_opt_state=(self.opt_state if self._zero1 else None),
                    data_axis=(axes or {}).get("data", "data"),
                    param_sharding=getattr(self, "_param_sh", None),
                    overlap=getattr(self, "_overlap_plan", None))
        return self._train_step

    def fit_scanned(self, data, labels=None, epochs: int = 1):
        """Whole-epoch fused training for DAG networks — see
        MultiLayerNetwork.fit_scanned (same engine, nn/training.fused_fit;
        same guards and per-epoch listener contract)."""
        from deeplearning4j_tpu.nn.training import fused_fit

        if self.params is None:
            self.init()
        if labels is not None:
            data = DataSet(data, labels)
        if isinstance(data, (DataSet, MultiDataSet)):
            data = ListDataSetIterator([data])
        batches = [self._batch_dict(self._to_mds(ds)) for ds in data]
        return fused_fit(self, batches, epochs)

    def _fit_with_solver(self, it, epochs: int):
        """CG/LBFGS/line-GD path (reference Solver dispatch — the graph
        delegates per-minibatch optimization exactly like MLN does)."""
        from deeplearning4j_tpu.optimize.solvers import Solver

        if self.conf.backprop_type in (BackpropType.TRUNCATED_BPTT,
                                       "truncated_bptt"):
            raise ValueError(
                "TRUNCATED_BPTT requires STOCHASTIC_GRADIENT_DESCENT; "
                "second-order solvers would differentiate the full sequence")
        solver = Solver(self)

        from deeplearning4j_tpu.data.pipeline import iter_prefetched

        for _ in range(epochs):
            it.reset()
            for _ds, batch in iter_prefetched(
                    it, lambda ds: self._batch_dict(self._to_mds(ds))):
                solver.optimize(batch, rng=self._next_rng())
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration_count)
        return self

    def _needs_tbptt(self, mds) -> bool:
        L = self.conf.tbptt_fwd_length
        return any(np.asarray(f).ndim == 3 and f.shape[1] > L
                   for f in mds.features)

    def _initial_carries(self, batch_size):
        """Zero carries for every recurrent layer vertex."""
        carries = {}
        for name, v in self.layer_vertices.items():
            impl = self.impls[name]
            if isinstance(v.layer, BaseRecurrentLayer) and hasattr(
                    impl, "initial_carry"):
                carries[name] = impl.initial_carry(v.layer, batch_size,
                                                   self.compute_dtype)
        return carries

    @staticmethod
    def _slice_time(arrs, t0, L):
        """Window [t0, t0+L) of every 3-D array; 2-D pass through unchanged
        (static inputs broadcast to all segments, as the reference's
        rnn-to-ff mixed graphs do)."""
        return tuple(None if a is None
                     else (a[:, t0:t0 + L] if np.asarray(a).ndim >= 3 else a)
                     for a in arrs)

    def _fit_tbptt(self, mds: MultiDataSet):
        """Truncated BPTT over the DAG (reference ComputationGraph fit tbptt
        branch): slide a tbptt_fwd_length window over time; recurrent-vertex
        carries thread between segments through the jitted step, gradients
        stop at segment boundaries."""
        T = max(f.shape[1] for f in mds.features if np.asarray(f).ndim == 3)
        L = self.conf.tbptt_fwd_length
        B = mds.features[0].shape[0]
        for lab in mds.labels:
            if np.asarray(lab).ndim != 3:
                raise ValueError(
                    "TRUNCATED_BPTT needs time-distributed labels "
                    f"[batch, time, n_out]; got shape {np.asarray(lab).shape}. "
                    "A per-sequence label would be counted once per segment "
                    "against mid-sequence activations — train with standard "
                    "BPTT (or a LastTimeStep head on full sequences) instead")
        carries = self._initial_carries(B)

        def mask_slice(masks, t0):
            if masks is None:
                return None
            return tuple(None if m is None
                         else (m[:, t0:t0 + L] if np.asarray(m).ndim >= 2
                               and m.shape[1] == T else m)
                         for m in masks)

        for t0 in range(0, T, L):
            sub = MultiDataSet(
                self._slice_time(mds.features, t0, L),
                self._slice_time(mds.labels, t0, L),
                mask_slice(mds.features_masks, t0),
                mask_slice(mds.labels_masks, t0),
            )
            batch = self._batch_dict(sub)
            batch["carries"] = carries
            self.params, self.opt_state, self.state, loss, extras = self._train_step(
                self.params, self.opt_state, self.state, self._next_rng(), batch)
            carries = extras.get("carries", carries)
            self.score_value = loss
            self.iteration_count += 1
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration_count)

    # -------------------------------------------------------------- pretrain
    def pretrain(self, it, epochs: int = 1):
        """Greedy layer-wise pretraining over the DAG (reference
        ComputationGraph.pretrain): for each pretrain-capable layer vertex in
        topological order, train its params on the activations feeding it."""
        if getattr(self, "_pp_plan", None) is not None:
            raise ValueError("pretrain is not supported while a pipeline "
                             "mesh is active — set_mesh(None) first")
        if self.params is None:
            self.init()
        if isinstance(it, (DataSet, MultiDataSet)):
            it = ListDataSetIterator([it])
        for name in self.topo:
            v = self.conf.vertices.get(name)
            if not isinstance(v, LayerVertexConf) or not v.layer.is_pretrain_layer():
                continue
            impl = self.impls[name]
            lc = v.layer
            tx = build_optimizer(self.conf.conf, {name: lc})
            # the optimizer's per-layer lr/updater overrides key on layer
            # names, so feed it {name: params} — not the bare inner dict
            opt = tx.init({name: self.params[name]})
            src = self.conf.vertex_inputs[name][0]
            is_input = src in self.conf.network_inputs

            @jax.jit
            def featurize(params, state, input_dict, _src=src, _v=v):
                acts, _, _ = self._forward(params, state, input_dict,
                                           train=False, rng=None, collect=True)
                x = acts[_src]
                if _v.preprocessor is not None:
                    x = _v.preprocessor.pre_process(x)
                return x

            @jax.jit
            def pstep(p, opt_state, rng, x, _impl=impl, _lc=lc, _tx=tx,
                      _name=name):
                loss, grads = jax.value_and_grad(
                    lambda q: _impl.pretrain_loss(_lc, q[_name], x, rng))(
                        {_name: p})
                updates, opt_state = _tx.update(grads, opt_state, {_name: p})
                return (optax.apply_updates({_name: p}, updates)[_name],
                        opt_state, loss)

            for _ in range(epochs):
                it.reset()
                while it.has_next():
                    mds = self._to_mds(it.next())
                    input_dict = dict(zip(self.conf.network_inputs,
                                          [jnp.asarray(f) for f in mds.features]))
                    if is_input and v.preprocessor is None:
                        x = jnp.asarray(input_dict[src], self.compute_dtype)
                    else:
                        x = featurize(self.params, self.state, input_dict)
                    p_new, opt, loss = pstep(self.params[name], opt,
                                             self._next_rng(), x)
                    self.params = dict(self.params, **{name: p_new})
                    self.score_value = loss
        return self

    # ------------------------------------------------------------- inference
    def output(self, *inputs, train: bool = False):
        """Outputs for given inputs (reference output). Returns a list (one
        per network output), or the single array if one output."""
        input_dict = dict(zip(self.conf.network_inputs, inputs))
        axes = getattr(self, "_mesh_axes", None)
        data_axis = (axes or {}).get("data", "data")
        has_data = (self._mesh is not None
                    and data_axis in self._mesh.axis_names)
        if self._output_jit is None:
            def _out(params, state, input_dict):
                if getattr(self, "_pp_plan", None) is not None:
                    # pipelined layout at rest: slice back to per-layer
                    # params inside the jit (free data movement)
                    params = self._pp_plan.to_canonical(params)
                ys, _, _ = self._forward(params, state, input_dict, train=False,
                                         rng=None)
                return ys
            if has_data:
                # distributed evaluation: batch sharded over the data axis
                # (reference EvaluateFlatMapFunction + Evaluation.merge)
                from deeplearning4j_tpu.nn.training import mesh_shardings

                repl, data = mesh_shardings(self._mesh, data_axis)
                # committed TP/PP params keep their placement (None);
                # plain-DP params are explicitly replicated
                p_in = (None if (getattr(self, "_pp_plan", None) is not None
                                 or getattr(self, "_param_sh", None)
                                 is not None) else repl)
                # process-spanning mesh: replicated output (a data-sharded
                # result spans non-addressable devices — unfetchable)
                out_sh = (repl if getattr(self, "_multiprocess", False)
                          else data)
                self._output_jit = jax.jit(
                    _out, in_shardings=(p_in, repl, data),
                    out_shardings=out_sh)
            else:
                self._output_jit = jax.jit(_out)
        input_dict = {k: jnp.asarray(v) for k, v in input_dict.items()}
        pad = 0
        if has_data:
            # pad batch to a multiple of the data axis, slice back below
            from deeplearning4j_tpu.nn.training import pad_batch_to_multiple

            input_dict, pad = pad_batch_to_multiple(
                input_dict, self._mesh.shape[data_axis])
            if getattr(self, "_multiprocess", False):
                # inference takes the FULL batch on every process (unlike
                # fit's per-process shards): globalize it data-sharded
                from deeplearning4j_tpu.distributed.global_mesh import (
                    globalize_full,
                )

                input_dict = {k: globalize_full(v, self._mesh, data_axis)
                              for k, v in input_dict.items()}
        ys = self._output_jit(self.params, self.state, input_dict)
        if pad:
            ys = [y[:-pad] for y in ys]
        return ys[0] if len(ys) == 1 else ys

    def predict(self, *inputs):
        out = self.output(*inputs)
        if isinstance(out, list):
            return [np.asarray(jnp.argmax(o, axis=-1)) for o in out]
        return np.asarray(jnp.argmax(out, axis=-1))

    def inference_fn(self):
        """A pure ``(params, state, x, mask=None) -> y`` inference-mode
        forward for external jit owners (the serving engine) — the DAG
        twin of MultiLayerNetwork.inference_fn. Serving dispatches on
        ONE padded input/output pair, so multi-input/multi-output graphs
        are rejected here rather than silently dropping streams."""
        ins = self.conf.network_inputs
        outs = self.conf.network_outputs
        if len(ins) != 1 or len(outs) != 1:
            raise ValueError(
                f"serving needs a single-input/single-output graph; this "
                f"one has inputs {list(ins)} and outputs {list(outs)}")
        name = ins[0]

        def fwd(params, state, x, mask=None):
            if getattr(self, "_pp_plan", None) is not None:
                params = self._pp_plan.to_canonical(params)
            masks = {} if mask is None else {name: mask}
            ys, _, _ = self._forward(params, state, {name: x},
                                     train=False, rng=None, masks=masks)
            return ys[0]
        return fwd

    def incremental_decode_fn(self, kv_dtype: str = "f32",
                              page_size: int = 16):
        """A pure jitted-step body ``(params, state, cache, token, pos)
        -> (probs, cache)`` — autoregressive decode with the KV cache as
        explicit threaded state (nn/decode.py). The productionized
        `rnn_time_step` contract for attention stacks: one new token per
        cache row at its own position, single-query attention against
        the cache, step cost independent of prompt length. External jit
        owners (serving/engine.py GenerationEngine) control the compile
        cache, exactly like `inference_fn`. kv_dtype="int8" reads/writes
        the quantized paged cache."""
        from deeplearning4j_tpu.nn.decode import make_decode_fn

        return make_decode_fn(self, kv_dtype, page_size)

    def prefill_fn(self, kv_dtype: str = "f32", page_size: int = 16):
        """The chunked-prefill twin of `incremental_decode_fn`:
        ``(params, state, cache, tokens, kmask, rows, start, last_idx)
        -> (probs_last, cache)`` fills cache rows from a bucket-shaped
        prompt chunk, reusing the autotuned flash kernels for the
        within-chunk attention (nn/decode.py)."""
        from deeplearning4j_tpu.nn.decode import make_prefill_fn

        return make_prefill_fn(self, kv_dtype, page_size)

    def verify_decode_fn(self, kv_dtype: str = "f32",
                         page_size: int = 16):
        """The speculative verification step ``(params, state, cache,
        tokens [B, K], pos) -> (probs [B, K, V], cache)`` — K candidate
        tokens per row checked in ONE fixed-shape call
        (nn/decode.make_verify_fn)."""
        from deeplearning4j_tpu.nn.decode import make_verify_fn

        return make_verify_fn(self, kv_dtype, page_size)

    def init_kv_cache(self, batch: int, capacity: int,
                      kv_dtype: str = "f32", page_size: int = 16):
        """Zeroed decode cache for `batch` rows of `capacity` key slots
        (nn/decode.init_cache)."""
        from deeplearning4j_tpu.nn.decode import init_cache

        return init_cache(self, batch, capacity, kv_dtype, page_size)

    def score(self, ds=None, training: bool = False):
        if ds is None:
            return self.score_value
        mds = self._to_mds(ds)
        loss, _ = self._loss(self._canonical_params(), self.state, None,
                             self._batch_dict(mds), train=training)
        return float(loss)

    def score_examples(self, ds, add_regularization: bool = False):
        """One score PER EXAMPLE [batch] over the DAG — summed across all
        output layers like score() (reference spark
        computationgraph/scoring/ScoreExamplesFunction.java). Inference-
        mode forward; `add_regularization` adds the network L1/L2 penalty
        to each example. With a mesh set, shards over the 'data' axis."""
        mds = self._to_mds(ds)
        batch = self._batch_dict(mds)
        key = bool(add_regularization)
        if key not in self._score_examples_jit:
            def _scores(params, state, batch):
                input_dict = dict(zip(self.conf.network_inputs,
                                      batch["features"]))
                masks = {}
                if batch.get("features_masks") is not None:
                    masks = {k: m for k, m in zip(self.conf.network_inputs,
                                                  batch["features_masks"])
                             if m is not None}
                acts, _, _ = self._forward(params, state, input_dict,
                                           train=False, rng=None,
                                           masks=masks, collect=True)
                per = 0.0
                labels_list = batch["labels"]
                lmasks = (batch.get("labels_masks")
                          or [None] * len(labels_list))
                cdtype = self.compute_dtype
                for out_name, labels, lmask in zip(
                        self.conf.network_outputs, labels_list, lmasks):
                    vconf = self.conf.vertices[out_name]
                    x = acts[self.conf.vertex_inputs[out_name][0]]
                    if vconf.preprocessor is not None:
                        x = vconf.preprocessor.pre_process(x)
                    p_out = params[out_name]
                    if cdtype != self.param_dtype:
                        p_out = tree_cast(p_out, cdtype)
                    per = per + self.impls[out_name].loss(
                        vconf.layer, p_out, x, labels, train=False,
                        rng=None, mask=lmask, per_example=True)
                if add_regularization:
                    reg = 0.0
                    for name, v in self.layer_vertices.items():
                        reg = reg + l1_l2_penalty(v.layer, params[name])
                    per = per + reg
                return per

            axes = getattr(self, "_mesh_axes", None)
            data_axis = (axes or {}).get("data", "data")
            if (self._mesh is not None
                    and data_axis in self._mesh.axis_names):
                from deeplearning4j_tpu.nn.training import mesh_shardings

                repl, data = mesh_shardings(self._mesh, data_axis)
                p_in = (None if (getattr(self, "_pp_plan", None) is not None
                                 or getattr(self, "_param_sh", None)
                                 is not None) else repl)
                batch_sh = jax.tree.map(lambda _: data, batch)
                self._score_examples_jit[key] = jax.jit(
                    _scores, in_shardings=(p_in, repl, batch_sh),
                    out_shardings=data)
            else:
                self._score_examples_jit[key] = jax.jit(_scores)
        axes = getattr(self, "_mesh_axes", None)
        data_axis = (axes or {}).get("data", "data")
        params = self._canonical_params()
        if self._mesh is not None and data_axis in self._mesh.axis_names:
            from deeplearning4j_tpu.nn.training import pad_batch_to_multiple

            B = np.asarray(mds.features[0]).shape[0]
            batch, pad = pad_batch_to_multiple(
                batch, self._mesh.shape[data_axis])
            per = self._score_examples_jit[key](params, self.state, batch)
            return np.asarray(per)[:B]
        return np.asarray(
            self._score_examples_jit[key](params, self.state, batch))

    def evaluate(self, it, top_n: int = 1):
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        ev = Evaluation(top_n=top_n)
        if isinstance(it, (DataSet, MultiDataSet)):
            it = ListDataSetIterator([it])
        it.reset()
        while it.has_next():
            ds = it.next()
            mds = self._to_mds(ds)
            out = self.output(*mds.features)
            outs = out if isinstance(out, list) else [out]
            ev.eval(mds.labels[0], np.asarray(outs[0]),
                    mask=None if mds.labels_masks is None else mds.labels_masks[0])
        from deeplearning4j_tpu.telemetry import get_default as _telemetry

        _telemetry().eval(ev, top_n=top_n)  # no-op unless telemetry is on
        return ev

    # ------------------------------------------------- streaming RNN inference
    def rnn_clear_previous_state(self):
        self._rnn_carries = None

    def rnn_time_step(self, *inputs):
        """Stateful single/multi-step inference over the DAG (reference
        ComputationGraph.rnnTimeStep). Each input: [batch, n_in] (one step)
        or [batch, time, n_in] — ranks must agree across inputs; recurrent-
        vertex carries persist between calls so long sequences stream in
        chunks. Raises for layers that cannot stream causally (bidirectional
        LSTM, self-attention — the reference throws
        UnsupportedOperationException for these)."""
        if getattr(self, "_pp_plan", None) is not None:
            raise ValueError("rnn_time_step is not supported while a "
                             "pipeline mesh is active — set_mesh(None) first")
        for name, v in self.layer_vertices.items():
            if isinstance(v.layer, BaseRecurrentLayer) and not hasattr(
                    self.impls[name], "initial_carry"):
                raise ValueError(
                    f"rnn_time_step: layer '{name}' "
                    f"({type(v.layer).__name__}) cannot stream causally — it "
                    "needs the full sequence (reference throws "
                    "UnsupportedOperationException)")
        cdtype = self.compute_dtype
        ranks = {jnp.asarray(x).ndim for x in inputs}
        if len(ranks) > 1:
            raise ValueError(
                f"rnn_time_step: mixed input ranks {sorted(ranks)} — pass all "
                "inputs as [batch, n_in] or all as [batch, time, n_in]")
        single = ranks == {2}
        arrs = []
        for x in inputs:
            x = jnp.asarray(x)
            if jnp.issubdtype(x.dtype, jnp.floating):
                x = x.astype(cdtype)
            arrs.append(x[:, None, :] if single else x)
        carries = self._rnn_carries
        if carries is None:
            carries = self._initial_carries(arrs[0].shape[0])
        input_dict = dict(zip(self.conf.network_inputs, arrs))
        if self._rnn_jit is None:
            def _step(params, state, input_dict, carries):
                return self._forward(params, state, input_dict, train=False,
                                     rng=None, carries=carries)
            self._rnn_jit = jax.jit(_step)
        ys, _, new_carries = self._rnn_jit(self.params, self.state, input_dict,
                                           carries)
        self._rnn_carries = {**carries, **new_carries}
        outs = [y[:, -1, :] if single and y.ndim == 3 else y for y in ys]
        return outs[0] if len(outs) == 1 else outs

    def rnn_activate_using_stored_state(self, *inputs,
                                        training: bool = False,
                                        store_last_for_tbptt: bool = False):
        """Full-sequence activations from the STORED streaming state
        (reference rnnActivateUsingStoredState semantics on the graph):
        recurrent vertices resume from the rnn_time_step state; the stored
        state only advances when store_last_for_tbptt=True. Returns the
        acts dict {vertex_name: activation}."""
        cdtype = self.compute_dtype
        arrs = []
        for x in inputs:
            x = jnp.asarray(x)
            if jnp.issubdtype(x.dtype, jnp.floating):
                x = x.astype(cdtype)
            if x.ndim != 3:
                raise ValueError("rnn_activate_using_stored_state expects "
                                 f"[batch, time, n_in]; got {x.shape}")
            arrs.append(x)
        carries = self._rnn_carries
        if carries is None:
            carries = self._initial_carries(arrs[0].shape[0])
        input_dict = dict(zip(self.conf.network_inputs, arrs))
        acts, _, new_carries = self._forward(
            self.params, self.state, input_dict,
            train=training, rng=self._next_rng() if training else None,
            collect=True, carries=carries)
        if store_last_for_tbptt:
            self._rnn_carries = {**carries, **new_carries}
        return acts

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.params))

    def params_flat(self):
        leaves = jax.tree.leaves(self._canonical_params())
        return (np.concatenate([np.asarray(l).ravel() for l in leaves])
                if leaves else np.zeros(0))

    def set_params_flat(self, flat):
        canonical = self._canonical_params()
        leaves, treedef = jax.tree.flatten(canonical)
        out, off = [], 0
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(jnp.asarray(flat[off:off + n], l.dtype).reshape(l.shape))
            off += n
        params = jax.tree.unflatten(treedef, out)
        if getattr(self, "_pp_plan", None) is not None:
            self.params = self._pp_plan.to_pipelined(params)
        else:
            self.params = params
