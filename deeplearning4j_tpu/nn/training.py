"""Jitted training step assembly.

The reference's inner optimization block (SURVEY.md §3.1: computeGradientAndScore
→ updater → stepFunction.step) becomes ONE donated-buffer XLA computation:
loss+grad via jax.value_and_grad, gradient normalization, optax update,
parameter application. The host keeps only the minibatch loop.

Data parallelism: when a `mesh` is given, the step is jitted with batch
inputs sharded over the mesh's 'data' axis and params replicated — XLA
inserts the gradient allreduce over ICI automatically (the BASELINE.json
"param-avg → ICI allreduce" goal; replaces
SparkDl4jMultiLayer.runIteration's broadcast/accumulator round-trip,
reference spark/impl/multilayer/SparkDl4jMultiLayer.java:365-452).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax

from deeplearning4j_tpu.nn.updater import normalize_gradients


def zero1_opt_shardings(opt_state, mesh, axis: str = "data"):
    """Cross-replica weight-update sharding (ZeRO stage 1; the XLA
    formulation is arXiv:2004.13336 "Automatic Cross-Replica Sharding of
    Weight Update in Data-Parallel Training"): optimizer-state leaves
    shard their leading dim over the data axis when divisible, so each
    replica stores and updates only 1/n of the Adam moments — GSPMD turns
    the gradient allreduce into reduce-scatter + sharded update +
    all-gather of the new params."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    repl = NamedSharding(mesh, P())

    def leaf(x):
        shape = getattr(x, "shape", ())
        if len(shape) >= 1 and shape[0] >= n and shape[0] % n == 0:
            return NamedSharding(mesh, P(axis, *([None] * (len(shape) - 1))))
        return repl

    return jax.tree.map(leaf, opt_state)


def _make_overlap_core(loss_fn, mesh, plan, data_axis):
    """The shard_map heart of the overlap train step: per-shard backward
    on the local batch slice, then the bucketed per-bucket collectives of
    `parallel/overlap.bucketed_reduce` in reverse layer order. Each
    bucket's psum depends only on its own grad leaves, so XLA's
    async-collective scheduler overlaps reduction with the remaining
    backward + the already-reduced buckets' update dataflow — the
    arXiv:1810.11112 design, with XLA as the progress engine."""
    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.parallel.overlap import (
        bucketed_reduce,
        pmean_float_leaves,
    )
    from deeplearning4j_tpu.util.compat import shard_map

    def local_grads(params, state, rng, batch):
        # decorrelate per-shard dropout streams (same idiom as the SP
        # step); dropout-free steps are unaffected — their parity with
        # the monolithic formulation is the test_overlap contract
        rng = jax.random.fold_in(rng, jax.lax.axis_index(data_axis))
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, state, rng, batch
        )
        new_state, _extras = aux if isinstance(aux, tuple) else (aux, {})
        grads = bucketed_reduce(grads, plan, axis_name=data_axis)
        loss = jax.lax.pmean(loss, data_axis)
        # per-shard mutable state (BatchNorm running stats over the local
        # batch slice) leaves the step as the cross-replica average
        new_state = pmean_float_leaves(new_state, data_axis)
        return loss, grads, new_state

    return shard_map(
        local_grads, mesh=mesh,
        in_specs=(P(), P(), P(), P(data_axis)),
        out_specs=(P(), P(), P()),
        check_vma=False, axis_names={data_axis})


def make_train_step(loss_fn, tx, layer_confs_by_name, mesh=None,
                    donate=True, zero1_opt_state=None, data_axis="data",
                    param_sharding=None, overlap=None):
    """loss_fn(params, state, rng, batch) -> (loss, (new_state, extras)).

    batch is a dict pytree {features, labels, features_mask?, labels_mask?,
    carries?}; extras carries auxiliary outputs (e.g. RNN carries for TBPTT).
    Returns step(params, opt_state, state, rng, batch) -> (params, opt_state,
    state, loss, extras).

    zero1_opt_state: pass the CURRENT opt_state (with `mesh`) to shard the
    optimizer state over the data axis (see zero1_opt_shardings).

    data_axis: mesh axis name the batch shards over (None: replicated —
    e.g. a pure tensor-parallel mesh). param_sharding: a pytree of
    NamedShardings for the params (TP/EP placement from
    parallel/tensor_parallel.py) — optimizer-state moments then inherit
    their committed placement instead of being forced replicated.

    overlap: a `parallel/overlap.BucketPlan` — gradients are computed
    per-shard under shard_map and reduced bucket-by-bucket (reverse
    layer order) instead of through GSPMD's single end-of-backward
    allreduce, letting XLA overlap the collectives with the remaining
    backward/update compute. Pure-DP only (the `set_mesh(overlap=...)`
    entry validates roles); composes with zero1_opt_state — the
    optimizer update stays in the enclosing jit, so the reduce-scatter
    weight-update placement is unchanged. The overlap step does not
    thread TBPTT carries (extras is always empty).
    """
    if overlap is not None:
        if mesh is None:
            raise ValueError("overlap=BucketPlan requires a mesh")
        if param_sharding is not None:
            raise ValueError(
                "overlap composes with the 'data' role only; TP/EP "
                "param placement keeps the GSPMD step")
        if not data_axis or data_axis not in mesh.axis_names:
            raise ValueError(
                f"overlap needs data_axis bound to a mesh axis (got "
                f"{data_axis!r}; mesh has {mesh.axis_names})")
        core = _make_overlap_core(loss_fn, mesh, overlap, data_axis)

        def step(params, opt_state, state, rng, batch):
            loss, grads, new_state = core(params, state, rng, batch)
            grads = normalize_gradients(grads, layer_confs_by_name)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, new_state, loss, {}
    else:
        def step(params, opt_state, state, rng, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, state, rng, batch
            )
            new_state, extras = aux if isinstance(aux, tuple) else (aux, {})
            grads = normalize_gradients(grads, layer_confs_by_name)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, new_state, loss, extras

    donate_argnums = (0, 1, 2) if donate else ()
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(mesh, P())
        data = (NamedSharding(mesh, P(data_axis))
                if data_axis and data_axis in mesh.axis_names else repl)
        p_sh = param_sharding if param_sharding is not None else repl
        if zero1_opt_state is not None:
            opt_in = opt_out = zero1_opt_shardings(
                zero1_opt_state, mesh, axis=data_axis)
        elif param_sharding is not None:
            # moments were committed alongside the params; None lets jit
            # respect (in) and propagate (out) that placement
            opt_in = opt_out = None
        else:
            opt_in = opt_out = repl
        # sharding pytree prefixes: one sharding per argument applies to all
        # its leaves — batch leaves are sharded on the data mesh axis
        return jax.jit(
            step,
            donate_argnums=donate_argnums,
            in_shardings=(p_sh, opt_in, repl, repl, data),
            out_shardings=(p_sh, opt_out, repl, repl, repl),
        )
    return jax.jit(step, donate_argnums=donate_argnums)


def make_scanned_fit(step):
    """Wrap a train step into a whole-epoch jitted scan.

    All minibatches live on device stacked on a leading axis; one dispatch
    runs the entire epoch (the fit()-path MFU mode: no per-batch host
    round-trips — on a remote-device link the per-dispatch latency
    otherwise dominates small steps). Returns
    run(params, opt_state, state, rng, batches, n_epochs) ->
    (params, opt_state, state, losses [n_epochs, n_batches]).
    """

    def run(params, opt_state, state, rng, batches, *, n_epochs):
        def epoch(carry, _):
            params, opt_state, state, rng = carry

            def one(carry, batch):
                params, opt_state, state, rng = carry
                rng, k = jax.random.split(rng)
                params, opt_state, state, loss, _ = step(
                    params, opt_state, state, k, batch)
                return (params, opt_state, state, rng), loss

            carry, losses = jax.lax.scan(
                one, (params, opt_state, state, rng), batches)
            return carry, losses

        (params, opt_state, state, _), losses = jax.lax.scan(
            epoch, (params, opt_state, state, rng), None, length=n_epochs)
        return params, opt_state, state, losses

    return jax.jit(partial(run), static_argnames=("n_epochs",))


def stack_batches(batch_dicts):
    """Stack per-batch dicts (uniform shapes) on a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *batch_dicts)


def fused_fit(net, batches, epochs):
    """Shared fit_scanned engine for both network containers.

    Guards against config modes the fused scan cannot honor (fit()'s
    dispatch would route them elsewhere), checks batch uniformity on full
    tree structure + every leaf shape, runs the scan, and updates
    iteration/epoch counters and listeners per epoch with that epoch's
    mean score.
    """
    from deeplearning4j_tpu.nn.conf.enums import (
        BackpropType,
        OptimizationAlgorithm,
    )

    conf = net.conf
    g = conf.conf
    if conf.pretrain:
        raise ValueError("fit_scanned does not support layerwise "
                         "pretraining — call pretrain()/fit() first")
    if not conf.backprop:
        raise ValueError("fit_scanned needs backprop=True")
    if str(g.optimization_algo) != str(
            OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT):
        raise ValueError(
            f"fit_scanned supports SGD-family training only; "
            f"{g.optimization_algo!r} routes through the Solver path — "
            "use fit()")
    if str(conf.backprop_type) in (str(BackpropType.TRUNCATED_BPTT),
                                   "truncated_bptt"):
        raise ValueError("fit_scanned does not implement TBPTT — use fit()")
    if getattr(g, "iterations", 1) > 1:
        raise ValueError("fit_scanned runs one optimizer pass per batch; "
                         "iterations>1 needs fit()")
    if not batches:
        return net
    structs = {jax.tree.structure(b) for b in batches}
    shapes = {tuple(l.shape for l in jax.tree.leaves(b)) for b in batches}
    if len(structs) > 1 or len(shapes) > 1:
        raise ValueError(
            "fit_scanned needs uniform batch shapes — drop or pad the "
            "ragged tail batch, or use fit()")
    stacked = stack_batches(batches)
    first_dispatch = net._scan_fit is None
    if first_dispatch:
        net._scan_fit = make_scanned_fit(net._get_train_step())
    # telemetry span around the scan dispatch: the FIRST dispatch blocks
    # on trace+compile (the "compile" span — the wall-clock XProf can't
    # cheaply give); later dispatches enqueue asynchronously, so their
    # "step_scan" span measures dispatch, not execution. A NullRecorder
    # (telemetry disabled — the default) makes this a no-op.
    from deeplearning4j_tpu.telemetry import get_default as _telemetry

    rec = _telemetry()
    rng = net._next_rng()
    with rec.span("compile" if first_dispatch else "step_scan",
                  what="fit_scanned", epochs=epochs,
                  n_batches=len(batches)):
        net.params, net.opt_state, net.state, losses = net._scan_fit(
            net.params, net.opt_state, net.state, rng, stacked,
            n_epochs=epochs)
    if first_dispatch:
        # compiled-cost harvest, warmup-only: lower() AFTER the warm
        # dispatch is a jaxpr-cache hit (no retrace); the shapes match
        # because the scan returned same-shaped trees
        from deeplearning4j_tpu.telemetry.costbook import CostBook

        book = getattr(net, "_cost_book", None)
        if book is None or book.recorder is not rec:
            book = CostBook(rec)
            try:
                net._cost_book = book
            except Exception:
                pass
        book.record("fit_scanned", [int(epochs), len(batches)],
                    net._scan_fit,
                    (net.params, net.opt_state, net.state, rng, stacked),
                    kwargs={"n_epochs": epochs})
    per_epoch = losses.mean(axis=1)
    nb = len(batches)
    if net.listeners:
        # counters advance WITH the callbacks so listeners that read model
        # state (per-epoch checkpointers keyed on iteration_count) see the
        # running values; per_epoch[e] device indexing happens only when
        # someone is listening — a bare fit_scanned stays one dispatch
        for e in range(epochs):
            net.iteration_count += nb
            if hasattr(net, "epoch_count"):
                net.epoch_count += 1
            net.score_value = per_epoch[e]
            for lst in net.listeners:
                lst.iteration_done(net, net.iteration_count)
    else:
        net.iteration_count += epochs * nb
        if hasattr(net, "epoch_count"):
            net.epoch_count += epochs
    net.score_value = losses[-1, -1]
    net._epoch_losses = per_epoch
    # one ledger-annotated memory event per fused dispatch when the env
    # cadence is on — the whole scan is one batch boundary
    from deeplearning4j_tpu.telemetry.memstat import sampler_for_net

    mem = sampler_for_net(net, rec)
    if mem.mem_every > 0:
        mem.sample("fit", iteration=net.iteration_count)
    return net


def fit_steps(net, batch_for_step, total_steps, *, on_step=None):
    """Global-step training loop — the elastic-recovery engine.

    Unlike epoch-oriented ``fit``, progress here is a single continuous
    step counter (``net.iteration_count``) that survives process death:
    a restored net resumes at its checkpointed step and
    ``batch_for_step(step)`` (1-based) regenerates the SAME global batch
    any fleet size would see for that step, so an interrupted-and-resumed
    run optimizes the identical sequence as an uninterrupted one.

    ``on_step(step)`` fires after each completed step — where
    `distributed/elastic.py` hangs its checkpoint cadence and the fault
    harness its kill/hang triggers (between one finished collective and
    the next, the same spot a real preemption lands). Emits one
    telemetry ``step`` event per step (no host sync: the device score is
    not read here).
    """
    from deeplearning4j_tpu.telemetry import get_default as _telemetry

    rec = _telemetry()
    while net.iteration_count < total_steps:
        step = net.iteration_count + 1
        net.fit(batch_for_step(step))
        # fit() advances iteration_count by the batches it consumed; one
        # DataSet per call keeps the counter == the global step
        if net.iteration_count != step:
            raise ValueError(
                f"batch_for_step({step}) yielded "
                f"{net.iteration_count - step + 1} optimizer passes — "
                "fit_steps needs exactly one DataSet per step (check "
                "`iterations` in the net config)")
        rec.step(step)
        if on_step is not None:
            on_step(step)
    return net


def mesh_shardings(mesh, data_axis: str = "data"):
    """(replicated, data-sharded) NamedShardings for a mesh data axis."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P()), NamedSharding(mesh, P(data_axis))


def pad_batch_to_multiple(tree, n):
    """Pad every leaf's batch dim to a multiple of n by repeating row 0;
    returns (padded_tree, pad). Sharded inference requires batch % n == 0;
    callers slice the pad rows back off the output."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return tree, 0
    B = leaves[0].shape[0]
    pad = (-B) % n
    if pad == 0:
        return tree, 0
    return jax.tree.map(
        lambda v: jnp.concatenate([v, jnp.repeat(v[:1], pad, axis=0)]),
        tree), pad


def make_eval_step(output_fn):
    """output_fn(params, state, features, mask) -> activations."""
    return jax.jit(partial(output_fn))


def tree_cast(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree,
    )
