"""Jitted training step assembly.

The reference's inner optimization block (SURVEY.md §3.1: computeGradientAndScore
→ updater → stepFunction.step) becomes ONE donated-buffer XLA computation:
loss+grad via jax.value_and_grad, gradient normalization, optax update,
parameter application. The host keeps only the minibatch loop.

Data parallelism: when a `mesh` is given, the step is jitted with batch
inputs sharded over the mesh's 'data' axis and params replicated — XLA
inserts the gradient allreduce over ICI automatically (the BASELINE.json
"param-avg → ICI allreduce" goal; replaces
SparkDl4jMultiLayer.runIteration's broadcast/accumulator round-trip,
reference spark/impl/multilayer/SparkDl4jMultiLayer.java:365-452).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax

from deeplearning4j_tpu.nn.updater import normalize_gradients


def make_train_step(loss_fn, tx, layer_confs_by_name, mesh=None,
                    donate=True):
    """loss_fn(params, state, rng, batch) -> (loss, (new_state, extras)).

    batch is a dict pytree {features, labels, features_mask?, labels_mask?,
    carries?}; extras carries auxiliary outputs (e.g. RNN carries for TBPTT).
    Returns step(params, opt_state, state, rng, batch) -> (params, opt_state,
    state, loss, extras).
    """

    def step(params, opt_state, state, rng, batch):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, state, rng, batch
        )
        new_state, extras = aux if isinstance(aux, tuple) else (aux, {})
        grads = normalize_gradients(grads, layer_confs_by_name)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, new_state, loss, extras

    donate_argnums = (0, 1, 2) if donate else ()
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P("data"))
        # sharding pytree prefixes: one sharding per argument applies to all
        # its leaves — batch leaves are sharded on the 'data' mesh axis
        return jax.jit(
            step,
            donate_argnums=donate_argnums,
            in_shardings=(repl, repl, repl, repl, data),
            out_shardings=(repl, repl, repl, repl, repl),
        )
    return jax.jit(step, donate_argnums=donate_argnums)


def make_eval_step(output_fn):
    """output_fn(params, state, features, mask) -> activations."""
    return jax.jit(partial(output_fn))


def tree_cast(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree,
    )
