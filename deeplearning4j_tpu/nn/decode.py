"""Incremental autoregressive decode with an explicit KV cache.

The reference's `rnnTimeStep` (MultiLayerNetwork.java:2147) is a
stateful streaming-inference contract that our SelfAttention layers
reject — attention "needs the full sequence" — so until r11 serving
re-ran the whole forward per generated token: N tokens cost N
full-sequence forwards. This module is the productionized incremental
contract for transformer stacks, on BOTH containers:

* ``make_decode_fn(net)`` — a pure jitted-step body
  ``(params, state, cache, token, pos) -> (probs, cache)``: one new
  token per cache row, positions per row (continuous batching mixes
  rows at different depths), the KV cache threaded as explicit state.
  Attention is single-query against the cache
  (ops/decode_attention.py, `decode_attn` autotune family), so the
  step's cost is independent of how much prompt each row has.
* ``make_prefill_fn(net)`` — the chunked-prefill body
  ``(params, state, cache, tokens, kmask, rows, start, last_idx) ->
  (probs_last, cache)``: fills cache rows with a prompt chunk's K/V and
  returns the last real token's output row. Within-chunk attention
  reuses the autotuned flash kernels when the chunk is inside their
  envelope (flash_attention_lse_masked — the same dispatch discipline
  as training); the cross-chunk half (chunk queries against the
  already-written cache prefix) runs through `cache_attention`, and the
  two merge by the standard two-way LSE combine. `start` is per-row, so
  a long prompt prefills in several bucket-shaped calls — the serving
  engine interleaves decode steps between them.
* ``make_verify_fn(net)`` — the SPECULATIVE verification body
  ``(params, state, cache, tokens, pos) -> (probs, cache)``: K tokens
  per row at positions ``pos..pos+K-1`` in ONE fixed-shape step. All K
  keys are written before attending and each query row i gets
  ``key_limit = pos+i+1``, which is exactly causal including self — so
  row i's output is bit-identical to what i sequential decode steps
  would produce given the same inputs. Acceptance is therefore a pure
  host-side mask over the K output rows (serving/speculative.py); a
  rejected draft's stale K/V is invisible (key_limit) until the next
  verify window — which always starts at or before the stale region —
  overwrites it.
* ``init_cache(net, batch, capacity)`` — zeroed per-attention-layer
  K/V pytree ``{layer: {"k": [B, S, H, D], "v": ...}}`` (key position
  on axis 1 so per-position scatter writes are contiguous).

All three entry fns (and ``init_cache``) take ``kv_dtype`` ("f32" |
"int8") and ``page_size``: the int8 paged cache stores codes plus
per-(row, page, head) f32 scales (``{"k", "k_scale", "v", "v_scale"}``
entries), writes through ops/decode_attention.quantized_cache_update,
and attends through `cache_attention_q8` (dequantize-in-the-scan) —
~4x less HBM per slot, gated on greedy-sequence parity vs the f32
cache in the serving replay.

Both fns are pure (no net mutation, no rng) so an external jit owner —
the serving engine — controls the compile cache, exactly like
`inference_fn`. Supported graphs: single-input/single-output stacks of
time-pointwise layers (dense / embedding / layernorm / output heads /
activation / dropout) plus causal SelfAttention and PositionalEncoding;
elementwise/merge/scale/subset vertices ride along. Anything that mixes
time any other way (LSTMs, convolutions over time, bidirectional
attention) raises at build time with the offending layer named.

Equivalence contract (tier-1, tests/test_generation.py): greedy decode
through prefill + K incremental steps matches argmax over K
full-sequence forwards at atol 1e-5.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    BaseOutputLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    LayerNormalization,
    PositionalEncodingLayer,
    SelfAttentionLayer,
)
from deeplearning4j_tpu.nn.training import tree_cast
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.decode_attention import (
    cache_attention,
    cache_attention_q8,
    quantized_cache_update,
)

_POINTWISE = (DenseLayer, EmbeddingLayer, LayerNormalization,
              BaseOutputLayer, ActivationLayer, DropoutLayer)

_NEG_INF = -1e30


# ------------------------------------------------------------- model plan

class _Op:
    """One traversal step: a layer or a non-layer vertex."""

    __slots__ = ("kind", "name", "conf", "impl", "preproc", "inputs")

    def __init__(self, kind, name, conf, impl, preproc, inputs):
        self.kind = kind
        self.name = name
        self.conf = conf
        self.impl = impl
        self.preproc = preproc
        self.inputs = inputs


def _plan(net):
    """-> (input_name, output_name, [ _Op ]) for either container,
    validating every layer/vertex is incrementally decodable."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    problems, ops = [], []
    if isinstance(net, ComputationGraph):
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ElementWiseVertexConf,
            LayerVertexConf,
            MergeVertexConf,
            ScaleVertexConf,
            SubsetVertexConf,
        )

        ins, outs = net.conf.network_inputs, net.conf.network_outputs
        if len(ins) != 1 or len(outs) != 1:
            raise ValueError(
                "incremental decode needs a single-input/single-output "
                f"graph; this one has inputs {list(ins)} and outputs "
                f"{list(outs)}")
        for name in net.topo:
            if name in ins:
                continue
            vconf = net.conf.vertices[name]
            inputs = list(net.conf.vertex_inputs[name])
            if isinstance(vconf, LayerVertexConf):
                lc = vconf.layer
                if not _decodable_layer(lc):
                    problems.append(f"{name} ({type(lc).__name__})")
                ops.append(_Op("layer", name, lc, net.impls[name],
                               vconf.preprocessor, inputs))
            elif isinstance(vconf, (ElementWiseVertexConf, MergeVertexConf,
                                    ScaleVertexConf, SubsetVertexConf)):
                ops.append(_Op("vertex", name, vconf, None, None, inputs))
            else:
                problems.append(f"{name} ({type(vconf).__name__})")
        in_name, out_name = ins[0], outs[0]
    else:
        prev = "__input__"
        for i, (name, lc, impl) in enumerate(zip(
                net.layer_names, net.layer_confs, net.impls)):
            if not _decodable_layer(lc):
                problems.append(f"{name} ({type(lc).__name__})")
            ops.append(_Op("layer", name, lc, impl,
                           net.conf.get_preprocessor(i), [prev]))
            prev = name
        in_name, out_name = "__input__", prev
    if problems:
        raise ValueError(
            "incremental decode supports transformer stacks (pointwise "
            "layers + causal SelfAttention + PositionalEncoding); these "
            "cannot stream one token at a time: " + ", ".join(problems))
    return in_name, out_name, ops


def _decodable_layer(lc) -> bool:
    if isinstance(lc, SelfAttentionLayer):
        return bool(lc.causal)  # non-causal attention reads the future
    if isinstance(lc, PositionalEncodingLayer):
        return True
    return isinstance(lc, _POINTWISE)


def attention_specs(net):
    """[(layer_name, n_heads, head_dim)] for every attention layer —
    the cache layout contract init_cache allocates by."""
    _, _, ops = _plan(net)
    return [(op.name, op.conf.n_heads, op.conf.n_out // op.conf.n_heads)
            for op in ops
            if op.kind == "layer" and isinstance(op.conf,
                                                 SelfAttentionLayer)]


def init_cache(net, batch: int, capacity: int, kv_dtype: str = "f32",
               page_size: int = 16):
    """Zeroed KV cache: {layer: {"k": [batch, capacity, H, D], "v":
    ...}} in the net's compute dtype. `capacity` is the per-row key
    budget (prompt + generated, page-quantized by the serving layer).
    kv_dtype="int8" stores int8 codes plus per-(row, page, head) f32
    scales ({"k", "k_scale", "v", "v_scale"} entries); capacity must
    sit on the page grid."""
    if kv_dtype == "int8":
        if capacity % page_size != 0:
            raise ValueError(
                f"int8 cache needs page-quantized capacity; {capacity} "
                f"is not a multiple of page_size {page_size}")
        n_pages = capacity // page_size
        return {name: {
            "k": jnp.zeros((batch, capacity, H, D), jnp.int8),
            "k_scale": jnp.zeros((batch, n_pages, H), jnp.float32),
            "v": jnp.zeros((batch, capacity, H, D), jnp.int8),
            "v_scale": jnp.zeros((batch, n_pages, H), jnp.float32)}
            for name, H, D in attention_specs(net)}
    dtype = net.compute_dtype
    return {name: {"k": jnp.zeros((batch, capacity, H, D), dtype),
                   "v": jnp.zeros((batch, capacity, H, D), dtype)}
            for name, H, D in attention_specs(net)}


def _cache_write(entry, k_new, v_new, rows, positions, kv_dtype,
                 page_size):
    """Write k_new/v_new [b, T, H, D] at (rows x positions [b, T]) —
    the dtype-dispatched cache scatter. Out-of-range positions (the
    engine's inactive-row scratch / a speculative tail past capacity)
    are dropped on both paths: the f32 scatter by jax's out-of-bounds
    default, the int8 path inside quantized_cache_update."""
    if kv_dtype == "int8":
        ck, ks = quantized_cache_update(entry["k"], entry["k_scale"],
                                        k_new, rows, positions, page_size)
        cv, vs = quantized_cache_update(entry["v"], entry["v_scale"],
                                        v_new, rows, positions, page_size)
        return {"k": ck, "k_scale": ks, "v": cv, "v_scale": vs}
    ck = entry["k"].at[rows[:, None], positions].set(
        k_new.astype(entry["k"].dtype))
    cv = entry["v"].at[rows[:, None], positions].set(
        v_new.astype(entry["v"].dtype))
    return {"k": ck, "v": cv}


def _cache_attend(entry, qh, key_limit, kv_dtype, page_size, rows=None):
    """Attend qh [b, H, Tq, D] against a cache entry with per-query
    visible-key bounds — dtype-dispatched. `rows` gathers a row subset
    first (the prefill cross-chunk path)."""
    if kv_dtype == "int8":
        k, v = entry["k"], entry["v"]
        ks, vs = entry["k_scale"], entry["v_scale"]
        if rows is not None:
            k, v, ks, vs = k[rows], v[rows], ks[rows], vs[rows]
        return cache_attention_q8(qh, k, v, ks, vs, key_limit, page_size)
    k, v = entry["k"], entry["v"]
    if rows is not None:
        k, v = k[rows], v[rows]
    return cache_attention(qh, k, v, key_limit)


# ------------------------------------------------------------ shared math

def _sinusoidal_at(positions, d, dtype):
    """Sinusoidal encodings at explicit positions [...] -> [..., d] —
    the per-position twin of PositionalEncodingImpl._sinusoidal (same
    f32 math, cast at the end, so decode matches the full forward)."""
    pos = positions.astype(jnp.float32)[..., None]
    dim = jnp.arange(0, d, 2).astype(jnp.float32)
    angle = pos / jnp.power(10000.0, dim / d)
    pe = jnp.zeros(positions.shape + (d,), jnp.float32)
    pe = pe.at[..., 0::2].set(jnp.sin(angle))
    pe = pe.at[..., 1::2].set(jnp.cos(angle[..., : d // 2]))
    return pe.astype(dtype)


def _dense_lse(qh, kh, vh, kmask):
    """Within-chunk causal attention with (out, lse) — the fallback for
    chunk shapes outside the flash envelope (tiny serving buckets, CPU
    tier-1). qh/kh/vh [b, H, T, D]; kmask [b, T]. f32 softmax like
    every other attention path."""
    D, T = qh.shape[-1], qh.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.float32(D))
    cm = jnp.tril(jnp.ones((T, T), bool))
    s = jnp.where(cm, s, _NEG_INF)
    s = jnp.where(kmask[:, None, None, :].astype(bool), s, _NEG_INF)
    m = s.max(-1)
    p = jnp.exp(s - m[..., None])
    l = p.sum(-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh.astype(jnp.float32))
    o = o / jnp.maximum(l, 1e-30)[..., None]
    return o.astype(qh.dtype), m + jnp.log(jnp.maximum(l, 1e-30))


def _chunk_self_lse(qh, kh, vh, kmask):
    """Within-chunk causal attention (out, lse), through the autotuned
    flash kernels when the chunk is inside their envelope — the prefill
    half of the "reuse the flash kernels" contract."""
    from deeplearning4j_tpu.ops import flash_attention as fa

    b, H, T, D = qh.shape
    if fa.supports(qh.shape, causal=True, dropout=0.0, mask=kmask):
        # flat [b*H, T, D] layout is b-major, so the key mask repeats
        # per head within each batch row
        km = jnp.repeat(jnp.asarray(kmask, jnp.float32), H,
                        axis=0)[:, None, :]
        o, lse = fa.flash_attention_lse_masked(
            qh.reshape(b * H, T, D), kh.reshape(b * H, T, D),
            vh.reshape(b * H, T, D), km, 1.0 / float(D) ** 0.5, True)
        return (o.reshape(b, H, T, D),
                lse.reshape(b, H, T).astype(jnp.float32))
    return _dense_lse(qh, kh, vh, kmask)


def _merge_lse(o1, lse1, o2, lse2):
    """Two-way blockwise softmax merge (the ring/chunk-loop combine):
    each part carries its own lse; fully-masked parts (lse at the mask
    floor) weigh to zero."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    denom = jnp.maximum(w1 + w2, 1e-30)[..., None]
    o = (o1.astype(jnp.float32) * w1[..., None]
         + o2.astype(jnp.float32) * w2[..., None]) / denom
    return o.astype(o1.dtype)


# -------------------------------------------------------------- the walk

def _walk(net, ops, in_name, out_name, params, state, x0, attn, posenc):
    """Topo traversal with inference semantics (train=False, no rng),
    attention/posenc routed to the supplied handlers. Mirrors the
    containers' _forward dtype policy: float inputs and per-layer params
    cast to the compute dtype."""
    cdtype = net.compute_dtype
    pdtype = net.param_dtype
    x0 = jnp.asarray(x0)
    if jnp.issubdtype(x0.dtype, jnp.floating):
        x0 = x0.astype(cdtype)
    acts = {in_name: x0}
    for op in ops:
        inputs = [acts[i] for i in op.inputs]
        if op.kind == "layer":
            x = inputs[0]
            if op.preproc is not None:
                x = op.preproc.pre_process(x)
            p = params.get(op.name, {})
            if cdtype != pdtype:
                p = tree_cast(p, cdtype)
            if isinstance(op.conf, SelfAttentionLayer):
                y = attn(op.name, op.conf, p, x)
            elif isinstance(op.conf, PositionalEncodingLayer):
                y = posenc(op.name, op.conf, p, x)
            else:
                y, _ = op.impl.apply(op.conf, p, state.get(op.name, {}),
                                     x, train=False, rng=None)
            acts[op.name] = y
        else:
            acts[op.name] = _vertex(op.conf, inputs)
    return acts[out_name]


def _vertex(vconf, inputs):
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ElementWiseVertexConf,
        MergeVertexConf,
        ScaleVertexConf,
        SubsetVertexConf,
    )

    if isinstance(vconf, MergeVertexConf):
        return jnp.concatenate(inputs, axis=-1)
    if isinstance(vconf, ScaleVertexConf):
        return inputs[0] * vconf.scale
    if isinstance(vconf, SubsetVertexConf):
        return inputs[0][..., vconf.from_idx:vconf.to_idx + 1]
    if isinstance(vconf, ElementWiseVertexConf):
        op = vconf.op
        out = inputs[0]
        for x in inputs[1:]:
            if op == "add":
                out = out + x
            elif op == "subtract":
                out = out - x
            elif op == "product":
                out = out * x
            elif op == "max":
                out = jnp.maximum(out, x)
            elif op == "average":
                out = out + x
            else:
                raise ValueError(f"elementwise op {op}")
        if op == "average":
            out = out / len(inputs)
        return out
    raise ValueError(f"unhandled vertex {type(vconf).__name__}")


def _split_heads(t, H):
    b, T, n = t.shape
    return t.reshape(b, T, H, n // H)


def _as_seq(x):
    """Re-expand [B, d] to [B, 1, d]. EmbeddingImpl squeezes a [B, 1]
    index column to [B] (reference EmbeddingLayer is feed-forward), so a
    single-token walk's activations can arrive 2-D; adding a [B, 1, d]
    positional term to a 2-D [B, d] would BROADCAST to [B, B, d] and
    silently hand every row past 0 row 0's features. Every handler that
    mixes x with per-row position data goes through this first."""
    return x[:, None, :] if x.ndim == 2 else x


# ------------------------------------------------------------ entry fns

def make_decode_fn(net, kv_dtype: str = "f32", page_size: int = 16):
    """-> pure ``step(params, state, cache, token, pos) -> (probs,
    cache)``. token [B] int32; pos [B] int32 is the position the token
    OCCUPIES (0-based — a row whose prompt filled [0, L) decodes its
    first generated token at pos=L). probs [B, V] is the output layer's
    activation row for that token; cache comes back with the token's
    K/V written at (row, pos)."""
    in_name, out_name, ops = _plan(net)

    def step(params, state, cache, token, pos):
        B = token.shape[0]
        new_cache = dict(cache)
        rows = jnp.arange(B)
        positions = pos[:, None]                           # [B, 1]

        def attn(name, conf, p, x):
            H, n = conf.n_heads, conf.n_out
            x = _as_seq(x)
            qkv = x[:, 0, :] @ p["Wqkv"] + p["bqkv"]       # [B, 3n]
            q, k_new, v_new = jnp.split(qkv, 3, axis=-1)
            Dh = n // H
            entry = _cache_write(
                new_cache[name], k_new.reshape(B, 1, H, Dh),
                v_new.reshape(B, 1, H, Dh), rows, positions,
                kv_dtype, page_size)
            new_cache[name] = entry
            qh = q.reshape(B, H, 1, Dh)
            o, _ = _cache_attend(entry, qh, (pos + 1)[:, None],
                                 kv_dtype, page_size)
            y = o[:, :, 0, :].reshape(B, n) @ p["Wo"] + p["bo"]
            return get_activation(conf.activation or "identity")(
                y)[:, None, :]

        def posenc(name, conf, p, x):
            x = _as_seq(x)
            d = x.shape[-1]
            if conf.learned:
                pe = jnp.take(p["pe"], pos, axis=0)        # [B, d]
            else:
                pe = _sinusoidal_at(pos, d, x.dtype)
            return x + pe[:, None, :]

        probs = _as_seq(_walk(net, ops, in_name, out_name, params, state,
                              token[:, None], attn, posenc))
        return probs[:, 0, :], new_cache

    return step


def make_prefill_fn(net, kv_dtype: str = "f32", page_size: int = 16):
    """-> pure ``prefill(params, state, cache, tokens, kmask, rows,
    start, last_idx) -> (probs_last, cache)``. tokens [b, Tc] int32 (a
    bucket-shaped prompt chunk, zero-padded); kmask [b, Tc] (1 = real
    token); rows [b] — which cache rows this chunk fills; start [b] —
    the global position of the chunk's first token (0 for the first
    chunk; later chunks of a long prompt attend the cache prefix they
    already wrote); last_idx [b] — the LOCAL index of the last real
    token in this chunk (its output row is gathered device-side so only
    [b, V] comes home; pass Tc-1 for non-final chunks and ignore the
    result). Padded positions write ZERO K/V (masked) and are
    overwritten as decode advances."""
    in_name, out_name, ops = _plan(net)

    def prefill(params, state, cache, tokens, kmask, rows, start,
                last_idx):
        b, Tc = tokens.shape
        new_cache = dict(cache)
        local = jnp.arange(Tc)
        positions = start[:, None] + local[None, :]        # [b, Tc]

        def attn(name, conf, p, x):
            H, n = conf.n_heads, conf.n_out
            Dh = n // H
            x = _as_seq(x)
            qkv = x @ p["Wqkv"] + p["bqkv"]                # [b, Tc, 3n]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            keep = kmask[..., None, None]
            entry = _cache_write(
                new_cache[name], _split_heads(k, H) * keep,
                _split_heads(v, H) * keep, rows, positions,
                kv_dtype, page_size)
            new_cache[name] = entry
            qh = _split_heads(q, H).transpose(0, 2, 1, 3)  # [b, H, Tc, Dh]
            kh = _split_heads(k, H).transpose(0, 2, 1, 3)
            vh = _split_heads(v, H).transpose(0, 2, 1, 3)
            o1, lse1 = _chunk_self_lse(qh, kh, vh, kmask)
            # cross-chunk half: queries against the cache prefix this
            # row wrote before `start` (empty on the first chunk — its
            # lse sits at the mask floor and merges to weight zero)
            limit = jnp.broadcast_to(start[:, None], (b, Tc))
            o2, lse2 = _cache_attend(entry, qh, limit, kv_dtype,
                                     page_size, rows=rows)
            o = _merge_lse(o1, lse1, o2, lse2)
            y = o.transpose(0, 2, 1, 3).reshape(b, Tc, n)
            y = y @ p["Wo"] + p["bo"]
            return get_activation(conf.activation or "identity")(y)

        def posenc(name, conf, p, x):
            x = _as_seq(x)
            d = x.shape[-1]
            if conf.learned:
                pe = jnp.take(p["pe"], positions, axis=0)  # [b, Tc, d]
            else:
                pe = _sinusoidal_at(positions, d, x.dtype)
            return x + pe

        probs = _as_seq(_walk(net, ops, in_name, out_name, params, state,
                              tokens, attn, posenc))
        return probs[jnp.arange(b), last_idx, :], new_cache

    return prefill


def make_verify_fn(net, kv_dtype: str = "f32", page_size: int = 16):
    """-> pure ``verify(params, state, cache, tokens, pos) -> (probs,
    cache)`` — the speculative-decode verification step. tokens [B, K]
    int32 is each row's candidate window (its true last token followed
    by K-1 draft tokens); pos [B] is the position the FIRST token
    occupies. probs [B, K, V]: row i is the model's next-token output
    after consuming tokens[:, :i+1] — bit-identical to what i+1
    sequential `make_decode_fn` steps would produce, because all K K/Vs
    are written first and query row i attends with key_limit pos+i+1
    (causal including self). The host-side acceptance mask
    (serving/speculative.py) compares argmax rows against the drafts;
    rejected positions' stale K/V stays invisible until the next verify
    window overwrites it."""
    in_name, out_name, ops = _plan(net)

    def verify(params, state, cache, tokens, pos):
        B, K = tokens.shape
        new_cache = dict(cache)
        rows = jnp.arange(B)
        positions = pos[:, None] + jnp.arange(K)[None, :]  # [B, K]

        def attn(name, conf, p, x):
            H, n = conf.n_heads, conf.n_out
            Dh = n // H
            x = _as_seq(x)
            qkv = x @ p["Wqkv"] + p["bqkv"]                # [B, K, 3n]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            entry = _cache_write(
                new_cache[name], _split_heads(k, H), _split_heads(v, H),
                rows, positions, kv_dtype, page_size)
            new_cache[name] = entry
            qh = _split_heads(q, H).transpose(0, 2, 1, 3)  # [B, H, K, Dh]
            o, _ = _cache_attend(entry, qh, positions + 1, kv_dtype,
                                 page_size)
            y = o.transpose(0, 2, 1, 3).reshape(B, K, n)
            y = y @ p["Wo"] + p["bo"]
            return get_activation(conf.activation or "identity")(y)

        def posenc(name, conf, p, x):
            x = _as_seq(x)
            d = x.shape[-1]
            if conf.learned:
                pe = jnp.take(p["pe"], positions, axis=0)  # [B, K, d]
            else:
                pe = _sinusoidal_at(positions, d, x.dtype)
            return x + pe

        probs = _walk(net, ops, in_name, out_name, params, state,
                      tokens, attn, posenc)
        return probs, new_cache

    return verify
