"""Networks as layers (VERDICT r2 #8 / missing #3).

Reference: MultiLayerNetwork itself `implements ... Layer`
(nn/multilayer/MultiLayerNetwork.java:78), so whole networks nest inside
other networks or as ComputationGraph vertices. The TPU-native analogue is
a NetworkLayer config wrapping an inner MultiLayerConfiguration or
ComputationGraphConfiguration: init() materializes the inner network's
param/state pytrees as this layer's subtree, apply() runs the inner pure
forward — so jax.grad differentiates straight through the nested network
and the nested params train with the outer optimizer.

Notes:
- the inner net's output layer contributes its ACTIVATION (softmax etc.),
  not its loss — exactly the reference's activate() path for nested MLNs;
- inner per-layer l1/l2 penalties are not re-applied by the outer
  container (set them on the outer NetworkLayer if needed);
- inner graphs must be single-input/single-output to act as a layer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer
from deeplearning4j_tpu.nn.conf.serde import register_config
from deeplearning4j_tpu.nn.layers.base import LayerImpl, register_impl


@register_config
@dataclasses.dataclass
class NetworkLayer(Layer):
    """A whole network used as one layer (reference
    MultiLayerNetwork.java:78 `implements Layer`)."""

    conf: Optional[Any] = None  # MultiLayerConfiguration | ComputationGraphConfiguration

    def _inner(self):
        """Build (and cache) the inner container — structure only; params
        and state live in the OUTER network's pytrees."""
        net = getattr(self, "_inner_cache", None)
        if net is None:
            from deeplearning4j_tpu.nn.conf.graph_conf import (
                ComputationGraphConfiguration,
            )
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

            if self.conf is None:
                raise ValueError("NetworkLayer needs conf=<inner network "
                                 "configuration>")
            if isinstance(self.conf, ComputationGraphConfiguration):
                if (len(self.conf.network_inputs) != 1
                        or len(self.conf.network_outputs) != 1):
                    raise ValueError(
                        "a nested graph must have exactly one input and "
                        "one output to act as a layer")
                net = ComputationGraph(self.conf)
            else:
                net = MultiLayerNetwork(self.conf)
            object.__setattr__(self, "_inner_cache", net)
        return net

    def get_output_type(self, input_type: InputType) -> InputType:
        """Propagate the outer shape inference THROUGH the nested network
        (preprocessors included) so downstream n_in inference sees the
        inner net's true output size."""
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration,
            LayerVertexConf,
        )

        if isinstance(self.conf, ComputationGraphConfiguration):
            g = self.conf
            types = {g.network_inputs[0]: input_type}
            for name in g.topological_order():
                if name in g.network_inputs:
                    continue
                v = g.vertices[name]
                in_types = [types[i] for i in g.vertex_inputs[name]]
                if isinstance(v, LayerVertexConf):
                    t = in_types[0]
                    if v.preprocessor is not None:
                        t = v.preprocessor.get_output_type(t)
                    types[name] = v.layer.get_output_type(t)
                else:
                    types[name] = v.get_output_type(*in_types)
            return types[g.network_outputs[0]]
        t = input_type
        for i, lc in enumerate(self.conf.layers):
            proc = self.conf.get_preprocessor(i)
            if proc is not None:
                t = proc.get_output_type(t)
            t = lc.get_output_type(t)
        return t


@register_impl(NetworkLayer)
class NetworkLayerImpl(LayerImpl):
    def init(self, conf, rng, dtype):
        del rng  # the inner conf's own seed drives its init (reference
        # nested nets are initialized from their own configuration)
        net = conf._inner()
        net.init()
        params, state = net.params, net.state
        # the outer container owns the pytrees from here on
        net.params = None
        net.state = None
        net.opt_state = None
        return params, state

    def apply(self, conf, params, state, x, *, train=False, rng=None,
              mask=None):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        net = conf._inner()
        if isinstance(net, ComputationGraph):
            inp = net.conf.network_inputs[0]
            masks = {inp: mask} if mask is not None else None
            ys, new_state, _ = net._forward(params, state, {inp: x},
                                            train=train, rng=rng,
                                            masks=masks)
            return ys[0], new_state
        y, new_state, _ = net._forward(params, state, x, train=train,
                                       rng=rng, mask=mask)
        return y, new_state
