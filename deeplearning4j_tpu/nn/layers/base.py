"""Layer implementation protocol + registry.

The reference pairs every conf class with a hand-written layer impl holding
forward AND analytic backward (nn/layers/*, e.g. BaseLayer.java:361 preOutput,
:161 backward gemm) wired through LayerFactories. Here an impl provides only:

- init(conf, rng, dtype)    -> (params pytree, state pytree)
- apply(conf, params, state, x, train, rng, mask) -> (y, new_state)

Backward is always jax.grad through apply — there is no backprop code
anywhere in this framework. `state` carries non-trained buffers (BatchNorm
running stats); layers without state return {}.

Dropout/DropConnect (reference util/Dropout.java, inverted dropout applied to
the layer input at BaseLayer) is implemented here once, with keyed PRNG.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_IMPL_REGISTRY: dict[type, "LayerImpl"] = {}

# State-channel key for per-batch auxiliary losses (e.g. the MoE router
# load-balance loss). A layer may stash a scalar under this key in its
# returned state during training; the containers sum every such entry
# into the training loss and the key never persists into eval state.
AUX_LOSS_KEY = "__aux_loss__"


def pop_aux_losses(state):
    """Sum and REMOVE ephemeral `AUX_LOSS_KEY` scalars from a state pytree.
    Returns (total, cleaned_state). The key must not survive into the
    persisted state: it is per-batch, and leaving it in would change the
    state pytree structure between init ({}) and post-forward (breaking
    lax.scan carries and checkpoints)."""
    total = 0.0
    cleaned = {}
    for name, s in state.items():
        if isinstance(s, dict) and AUX_LOSS_KEY in s:
            total = total + s[AUX_LOSS_KEY]
            cleaned[name] = {k: v for k, v in s.items() if k != AUX_LOSS_KEY}
        else:
            cleaned[name] = s
    return total, cleaned


def register_impl(conf_cls):
    def wrap(impl_cls):
        _IMPL_REGISTRY[conf_cls] = impl_cls()
        return impl_cls

    return wrap


def get_impl(conf) -> "LayerImpl":
    for cls in type(conf).__mro__:
        impl = _IMPL_REGISTRY.get(cls)
        if impl is not None:
            return impl
    raise ValueError(f"No layer implementation registered for {type(conf).__name__}")


class LayerImpl:
    """Stateless singleton holding pure init/apply for one layer kind."""

    def init(self, conf, rng, dtype):
        return {}, {}

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        raise NotImplementedError

    # pretrain interface (AutoEncoder/RBM): returns (loss, params-grad-ready fn)
    def pretrain_loss(self, conf, params, x, rng):
        raise NotImplementedError(f"{type(self).__name__} is not a pretrain layer")


def apply_dropout(x, rate, rng, *, train):
    """Inverted dropout on the layer input (reference util/Dropout.applyDropout:31)."""
    if not train or rate in (None, 0.0) or rng is None:
        return x
    keep = 1.0 - rate
    m = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(m, x / keep, 0.0)


def apply_dropconnect(w, rate, rng, *, train):
    """DropConnect: drop weights instead of activations (reference Dropout.java)."""
    if not train or rate in (None, 0.0) or rng is None:
        return w
    keep = 1.0 - rate
    m = jax.random.bernoulli(rng, keep, w.shape)
    return jnp.where(m, w / keep, 0.0)


def l1_l2_penalty(conf, params):
    """Per-layer L1/L2 regularization on weight params only (reference
    BaseLayer calcL1/calcL2 — biases excluded)."""
    pen = 0.0
    l1 = getattr(conf, "l1", 0.0) or 0.0
    l2 = getattr(conf, "l2", 0.0) or 0.0
    if l1 == 0.0 and l2 == 0.0:
        return 0.0
    for name, p in params.items():
        if name.startswith("b") or name in ("gamma", "beta", "mean", "var"):
            continue
        if l1:
            pen = pen + l1 * jnp.sum(jnp.abs(p))
        if l2:
            pen = pen + 0.5 * l2 * jnp.sum(p * p)
    return pen
