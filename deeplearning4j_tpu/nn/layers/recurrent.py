"""Recurrent layer implementations: GravesLSTM (peepholes), LSTM, bidirectional
LSTM, GRU.

Reference: layers/recurrent/GravesLSTM.java + LSTMHelpers.java (fwd
activateHelper:50 — per-timestep loop with gate ops :155-180; bwd
backpropGradientHelper:210 — manual BPTT), GravesBidirectionalLSTM.java,
GRU.java; peephole parameter layout GravesLSTMParamInitializer.java:86-87
(input W nIn×4nL, recurrent W nL×(4nL+3) — the +3 columns are peepholes).

TPU-first design:
- activations are [batch, time, features]
- the input projection for ALL timesteps is one large [B*T, n_in]×[n_in, 4n]
  matmul (MXU-friendly), hoisted out of the recurrence
- the recurrence itself is `lax.scan` (compiled once, no Python loop)
- backward is jax.grad through the scan — no hand-written BPTT
- peepholes are stored as three [n_out] vectors rather than packed columns
- masking: a [B, T] mask freezes the carry and zeroes output at masked steps
  (reference per-layer mask support / setLayerMaskArrays)
- streaming inference (reference rnnTimeStep:2147): `step()` advances one
  timestep with an explicit carry pytree returned to the host
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers import (
    GravesBidirectionalLSTM,
    GravesLSTM,
    GRU,
    LSTM,
)
from deeplearning4j_tpu.nn.layers.base import LayerImpl, apply_dropout, register_impl
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import get_activation

_SIGMOID = jax.nn.sigmoid


def _lstm_init(conf, rng, dtype, peephole):
    n_in, n = conf.n_in, conf.n_out
    k1, k2, k3 = jax.random.split(rng, 3)
    params = {
        "W": init_weights(k1, (n_in, 4 * n), conf.weight_init, conf.dist, dtype,
                          fan_in=n_in, fan_out=n),
        "RW": init_weights(k2, (n, 4 * n), conf.weight_init, conf.dist, dtype,
                           fan_in=n, fan_out=n),
        "b": jnp.zeros((4 * n,), dtype).at[n:2 * n].set(conf.forget_gate_bias_init),
    }
    if peephole:
        # p_i, p_f act on c_{t-1}; p_o on c_t (Graves 2013 eqs. 7-9)
        params["pi"] = jnp.zeros((n,), dtype)
        params["pf"] = jnp.zeros((n,), dtype)
        params["po"] = jnp.zeros((n,), dtype)
    return params, {}


def _lstm_cell(params, act, peephole):
    n = params["RW"].shape[0]

    def cell(carry, zx_m):
        h, c = carry
        zx, m = zx_m  # zx: [B, 4n] precomputed x-projection; m: [B, 1] mask
        z = zx + h @ params["RW"]
        zi, zf, zg, zo = (z[:, :n], z[:, n:2 * n], z[:, 2 * n:3 * n], z[:, 3 * n:])
        if peephole:
            zi = zi + c * params["pi"]
            zf = zf + c * params["pf"]
        i = _SIGMOID(zi)
        f = _SIGMOID(zf)
        g = jnp.tanh(zg)
        c_new = f * c + i * g
        if peephole:
            zo = zo + c_new * params["po"]
        o = _SIGMOID(zo)
        h_new = o * act(c_new)
        if m is not None:
            h_new = jnp.where(m, h_new, h)
            c_new = jnp.where(m, c_new, c)
        return (h_new, c_new), h_new

    return cell


def _scan_time(cell, carry, zx, mask, reverse=False):
    # zx: [B, T, 4n] → scan over axis 1 via transpose to [T, B, 4n]
    zx_t = jnp.swapaxes(zx, 0, 1)
    m_t = None
    if mask is not None:
        m_t = jnp.swapaxes(mask.astype(bool)[..., None], 0, 1)  # [T, B, 1]
    else:
        m_t = jnp.ones((zx_t.shape[0], zx_t.shape[1], 1), bool)
    carry, ys = jax.lax.scan(cell, carry, (zx_t, m_t), reverse=reverse)
    return carry, jnp.swapaxes(ys, 0, 1)  # [B, T, n]


class _BaseLSTMImpl(LayerImpl):
    peephole = False

    def init(self, conf, rng, dtype):
        return _lstm_init(conf, rng, dtype, self.peephole)

    def initial_carry(self, conf, batch, dtype=jnp.float32):
        n = conf.n_out
        return (jnp.zeros((batch, n), dtype), jnp.zeros((batch, n), dtype))

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None,
              initial_carry=None, return_carry=False):
        if conf.dropout:
            x = apply_dropout(x, conf.dropout, rng, train=train)
        act = get_activation(conf.activation or "tanh")
        zx = x @ params["W"] + params["b"]  # [B, T, 4n] — one big MXU matmul
        carry = initial_carry or self.initial_carry(conf, x.shape[0], x.dtype)
        cell = _lstm_cell(params, act, self.peephole)
        carry, ys = _scan_time(cell, carry, zx, mask)
        if return_carry:
            return ys, state, carry
        return ys, state

    def step(self, conf, params, carry, x_t):
        """One streaming timestep (reference rnnTimeStep). x_t: [B, n_in]."""
        act = get_activation(conf.activation or "tanh")
        zx = x_t @ params["W"] + params["b"]
        cell = _lstm_cell(params, act, self.peephole)
        carry, h = cell(carry, (zx, None))
        return carry, h


@register_impl(GravesLSTM)
class GravesLSTMImpl(_BaseLSTMImpl):
    peephole = True


@register_impl(LSTM)
class LSTMImpl(_BaseLSTMImpl):
    peephole = False


@register_impl(GravesBidirectionalLSTM)
class BiLSTMImpl(LayerImpl):
    """Forward + backward Graves LSTMs, outputs summed (reference
    GravesBidirectionalLSTM merges directions additively)."""

    def init(self, conf, rng, dtype):
        kf, kb = jax.random.split(rng)
        pf, _ = _lstm_init(conf, kf, dtype, peephole=True)
        pb, _ = _lstm_init(conf, kb, dtype, peephole=True)
        return {"fwd": pf, "bwd": pb}, {}

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        if conf.dropout:
            x = apply_dropout(x, conf.dropout, rng, train=train)
        act = get_activation(conf.activation or "tanh")
        n = conf.n_out
        outs = []
        for key, reverse in (("fwd", False), ("bwd", True)):
            p = params[key]
            zx = x @ p["W"] + p["b"]
            carry = (jnp.zeros((x.shape[0], n), x.dtype), jnp.zeros((x.shape[0], n), x.dtype))
            cell = _lstm_cell(p, act, True)
            _, ys = _scan_time(cell, carry, zx, mask, reverse=reverse)
            outs.append(ys)
        return outs[0] + outs[1], state


@register_impl(GRU)
class GRUImpl(LayerImpl):
    """GRU (reference layers/recurrent/GRU.java): r/u gates + candidate."""

    def init(self, conf, rng, dtype):
        n_in, n = conf.n_in, conf.n_out
        k1, k2 = jax.random.split(rng)
        return {
            "W": init_weights(k1, (n_in, 3 * n), conf.weight_init, conf.dist, dtype,
                              fan_in=n_in, fan_out=n),
            "RW": init_weights(k2, (n, 3 * n), conf.weight_init, conf.dist, dtype,
                               fan_in=n, fan_out=n),
            "b": jnp.zeros((3 * n,), dtype),
        }, {}

    def initial_carry(self, conf, batch, dtype=jnp.float32):
        return jnp.zeros((batch, conf.n_out), dtype)

    def _cell(self, conf, params):
        n = conf.n_out
        act = get_activation(conf.activation or "tanh")

        def cell(h, zx_m):
            zx, m = zx_m
            zr = zx[:, :n] + h @ params["RW"][:, :n]
            zu = zx[:, n:2 * n] + h @ params["RW"][:, n:2 * n]
            r = _SIGMOID(zr)
            u = _SIGMOID(zu)
            zc = zx[:, 2 * n:] + (r * h) @ params["RW"][:, 2 * n:]
            c = act(zc)
            h_new = u * h + (1 - u) * c
            if m is not None:
                h_new = jnp.where(m, h_new, h)
            return h_new, h_new

        return cell

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None,
              initial_carry=None, return_carry=False):
        if conf.dropout:
            x = apply_dropout(x, conf.dropout, rng, train=train)
        zx = x @ params["W"] + params["b"]
        h0 = initial_carry if initial_carry is not None else self.initial_carry(
            conf, x.shape[0], x.dtype)
        carry, ys = _scan_time(self._cell(conf, params), h0, zx, mask)
        if return_carry:
            return ys, state, carry
        return ys, state

    def step(self, conf, params, carry, x_t):
        zx = x_t @ params["W"] + params["b"]
        cell = self._cell(conf, params)
        h, y = cell(carry, (zx, None))
        return h, y
