"""Mixture-of-Experts layer with top-k gating + expert parallelism (EP).

New TPU-first capability (no reference analogue — the reference predates
MoE): E expert FFNs with a learned router. The dense path computes every
expert for every token and masks by the top-k gate — compiler-friendly
(static shapes, no gather/scatter of token groups) and exact; the
expert-parallel path (parallel/expert_parallel.py) shards the expert
dimension over a mesh axis and psum-combines partial outputs, bitwise
matching the dense path on any device count.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import FeedForwardLayer
from deeplearning4j_tpu.nn.conf.serde import register_config
from deeplearning4j_tpu.nn.layers.base import (
    LayerImpl,
    apply_dropout,
    register_impl,
)
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import get_activation


@register_config
@dataclasses.dataclass
class MixtureOfExpertsLayer(FeedForwardLayer):
    """Top-k gated expert FFNs: y = sum_k gate_k * FFN_{e_k}(x)."""

    n_experts: int = 8
    top_k: int = 2
    d_hidden: int = 0  # defaults to 4*n_in

    def get_output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "recurrent":
            return InputType.recurrent(self.n_out, input_type.timeseries_length)
        return InputType.feed_forward(self.n_out)


def moe_gates(x2d, Wg, top_k):
    """Top-k renormalized softmax gates [N, E] (zeros outside the top-k)."""
    logits = x2d @ Wg                                     # [N, E]
    E = logits.shape[-1]
    top_vals, top_idx = jax.lax.top_k(logits, top_k)      # [N, k]
    probs = jax.nn.softmax(top_vals, axis=-1)             # renormalized
    gates = jnp.zeros((x2d.shape[0], E), logits.dtype).at[
        jnp.arange(x2d.shape[0])[:, None], top_idx].set(probs)
    return gates


def moe_expert_outputs(params, x2d, activation):
    """All experts applied to all tokens: [N, E, n_out]."""
    act = get_activation(activation)
    h = jnp.einsum("nd,edh->neh", x2d, params["We1"]) + params["be1"]
    h = act(h)
    return jnp.einsum("neh,eho->neo", h, params["We2"]) + params["be2"]


@register_impl(MixtureOfExpertsLayer)
class MixtureOfExpertsImpl(LayerImpl):
    def init(self, conf, rng, dtype):
        E = conf.n_experts
        D, O = conf.n_in, conf.n_out or conf.n_in
        H = conf.d_hidden or 4 * D
        kg, k1, k2 = jax.random.split(rng, 3)
        We1 = jnp.stack([
            init_weights(k, (D, H), conf.weight_init, conf.dist, dtype)
            for k in jax.random.split(k1, E)])
        We2 = jnp.stack([
            init_weights(k, (H, O), conf.weight_init, conf.dist, dtype)
            for k in jax.random.split(k2, E)])
        return {
            "Wg": init_weights(kg, (D, E), conf.weight_init, conf.dist, dtype),
            "We1": We1, "be1": jnp.zeros((E, H), dtype),
            "We2": We2, "be2": jnp.zeros((E, O), dtype),
        }, {}

    def apply(self, conf, params, state, x, *, train=False, rng=None,
              mask=None):
        if conf.dropout:
            x = apply_dropout(x, conf.dropout, rng, train=train)
        shape = x.shape
        x2d = x.reshape(-1, shape[-1])
        gates = moe_gates(x2d, params["Wg"], conf.top_k)   # [N, E]
        outs = moe_expert_outputs(params, x2d, conf.activation or "gelu")
        y = jnp.einsum("ne,neo->no", gates, outs)
        y = y.reshape(*shape[:-1], y.shape[-1])
        return y, state
