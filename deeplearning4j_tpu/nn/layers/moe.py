"""Mixture-of-Experts layer: top-k router with token-routed dispatch + EP.

New TPU-first capability (no reference analogue — the reference predates
MoE): E expert FFNs with a learned router. Two execution paths:

- ``routing="routed"`` (default): GShard/Switch-style capacity-factor
  einsum dispatch. Tokens are split into groups of ``router_group_size``;
  within each group every token's top-k experts claim a slot in that
  expert's capacity buffer (C = ceil(S * top_k * capacity_factor / E),
  token-order priority), a one-hot dispatch tensor [G,S,E,C] gathers the
  claimed tokens into [E,G,C,D], the expert FFNs run as batched einsums
  over the E-leading stacked weights, and a combine einsum (dispatch x
  renormalized gate) scatters results back. Everything is static-shaped
  einsum — MXU-friendly, differentiable, and GSPMD shards it over an
  'expert' mesh axis from the weight shardings alone (the combine's
  contraction over E becomes the psum; data-sharded tokens x
  expert-sharded buffers become the all-to-all). Tokens over capacity are
  dropped (contribute zero; the surrounding residual carries them) — the
  router is regularized toward balance by a Switch-style aux loss
  (``router_aux_weight``) surfaced through the layer-state channel as
  ``__aux_loss__`` and summed into the training loss by the containers.

- ``routing="dense"``: every expert on every token, zero-masked by the
  gate. Exact, smooth (finite-difference-checkable), no drops — the
  parity oracle for the routed path and the manual EP shard_map
  (parallel/expert_parallel.py). At top_k/E compute overcost E/top_k.

With ample capacity (capacity_factor >= E/top_k) the routed path drops
nothing and matches the dense path to float tolerance.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import FeedForwardLayer
from deeplearning4j_tpu.nn.conf.serde import register_config
from deeplearning4j_tpu.nn.layers.base import (
    AUX_LOSS_KEY,
    LayerImpl,
    apply_dropout,
    register_impl,
)
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import get_activation


@register_config
@dataclasses.dataclass
class MixtureOfExpertsLayer(FeedForwardLayer):
    """Top-k gated expert FFNs: y = sum_k gate_k * FFN_{e_k}(x)."""

    n_experts: int = 8
    top_k: int = 2
    d_hidden: int = 0  # defaults to 4*n_in
    routing: str = "routed"  # "routed" (capacity dispatch) | "dense" (oracle)
    capacity_factor: float = 1.25
    router_group_size: int = 0  # tokens per routing group; 0 = auto (256)
    router_aux_weight: float = 0.01  # Switch-style load-balance loss weight

    def get_output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "recurrent":
            return InputType.recurrent(self.n_out, input_type.timeseries_length)
        return InputType.feed_forward(self.n_out)


def moe_topk_from_logits(logits, top_k):
    """(gates [N, E], expert ids [N, k], renormalized probs [N, k]).

    For the practical regime (small k, modest E) the top-k runs as k
    argmax+mask passes and the gate matrix is built from one-hots —
    lax.top_k lowers to a full sort and the scatter writing [N, E] cost
    ~2 ms each at [16k, 8] on v5e (r4 trace); the iterative form fuses
    into cheap VPU elementwise work. Tie-breaking (first index wins)
    matches lax.top_k.
    """
    E = logits.shape[-1]
    N = logits.shape[0]
    if top_k <= 4 and E <= 64:
        x = logits
        onehots, vals, ids = [], [], []
        for _ in range(top_k):
            i = jnp.argmax(x, axis=-1)
            oh = jax.nn.one_hot(i, E, dtype=logits.dtype)   # [N, E]
            vals.append(jnp.max(x, axis=-1))
            onehots.append(oh)
            ids.append(i)
            x = jnp.where(oh > 0, jnp.finfo(x.dtype).min, x)
        probs = jax.nn.softmax(jnp.stack(vals, -1), axis=-1)  # [N, k]
        gates = sum(oh * probs[:, j:j + 1] for j, oh in enumerate(onehots))
        return gates, jnp.stack(ids, -1), probs
    top_vals, top_idx = jax.lax.top_k(logits, top_k)      # [N, k]
    probs = jax.nn.softmax(top_vals, axis=-1)             # renormalized
    gates = jnp.zeros((N, E), logits.dtype).at[
        jnp.arange(N)[:, None], top_idx].set(probs)
    return gates, top_idx, probs


def moe_gates_from_logits(logits, top_k):
    """Top-k renormalized softmax gates [N, E] (zeros outside the top-k)."""
    return moe_topk_from_logits(logits, top_k)[0]


def moe_gates(x2d, Wg, top_k):
    """Top-k renormalized softmax gates [N, E] (zeros outside the top-k)."""
    return moe_gates_from_logits(x2d @ Wg, top_k)


def moe_expert_outputs(params, x2d, activation):
    """All experts applied to all tokens: [N, E, n_out] (dense oracle)."""
    act = get_activation(activation)
    h = jnp.einsum("nd,edh->neh", x2d, params["We1"]) + params["be1"]
    h = act(h)
    return jnp.einsum("neh,eho->neo", h, params["We2"]) + params["be2"]


def moe_apply_dense(params, x2d, *, top_k, activation):
    """Dense-path MoE forward: compute-all-experts, gate-masked combine."""
    gates = moe_gates(x2d, params["Wg"], top_k)            # [N, E]
    outs = moe_expert_outputs(params, x2d, activation)     # [N, E, O]
    return jnp.einsum("ne,neo->no", gates, outs)


def expert_capacity(group_size, top_k, capacity_factor, n_experts):
    """Per-group per-expert capacity, rounded up to a multiple of 8
    (sublane-friendly), capped at group_size — a token claims a given
    expert at most once (the argmax gate masks each chosen expert), so
    an expert can never receive more than the group's tokens."""
    c = math.ceil(group_size * top_k * capacity_factor / n_experts)
    c = -(-c // 8) * 8
    return min(c, group_size)


def moe_load_balance_loss(logits, gates, top_k):
    """Switch Transformer aux loss (arXiv:2101.03961 eq. 4 generalized to
    top-k): E * sum_e f_e * P_e, where f_e is the fraction of routing
    assignments sent to expert e and P_e the mean full-softmax router
    probability. Minimized (=1) at uniform routing; gradient reaches the
    router only (f is piecewise-constant)."""
    E = logits.shape[-1]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)   # [N, E]
    frac = jnp.mean((gates > 0).astype(jnp.float32), axis=0) / top_k
    importance = jnp.mean(probs, axis=0)
    return E * jnp.sum(frac * importance)


# Routed dispatch implementation: "einsum" (GShard one-hot formulation,
# r5 default — with MXU-friendly float routing metadata) or "gather"
# (index-based take_along_axis/scatter). The r5 trace showed BOTH
# formulations' real cost was the routing METADATA — an s32 cumsum
# lowered to reduce-window (~1.2 ms/step) plus pred/s32 elementwise and
# small-axis gathers (several ms) — while the einsum dispatch itself is
# ~50 us of MXU time; the gather form additionally pays TPU's slow
# generic gather lowering (take_along_axis at ~50 GB/s effective). The
# einsum path therefore computes positions via a STRICTLY-LOWER-
# TRIANGULAR MATMUL (exclusive prefix counts on the MXU; counts <= S
# are exact in the f32 accumulator) and keeps every mask in the compute
# dtype — no s32/pred bands at all.
DISPATCH = "einsum"


def moe_apply_routed(params, x2d, *, top_k, capacity_factor, activation,
                     group_size=0, return_aux=False, dispatch=None):
    """Token-routed MoE forward via capacity-factor dispatch.

    Returns y [N, O] (and the unweighted load-balance aux loss when
    ``return_aux``). Within each group, slots are claimed in token order;
    a token whose expert buffer is full is dropped (zero output row).
    """
    N, D = x2d.shape
    E = params["We1"].shape[0]
    O = params["We2"].shape[-1]
    # default group 256: the r4 einsum dispatch cost scaled with group
    # size (one-hots ∝ S); the gather dispatch is size-insensitive but
    # the drop WINDOW semantics stay per-group, so the default holds
    S = group_size or min(N, 256)
    G = -(-N // S)
    pad = G * S - N

    logits = x2d @ params["Wg"]                            # [N, E]
    gates, top_idx, top_probs = moe_topk_from_logits(logits, top_k)
    aux = moe_load_balance_loss(logits, gates, top_k) if return_aux else None

    xp = jnp.pad(x2d, ((0, pad), (0, 0))) if pad else x2d
    gg = (jnp.pad(gates, ((0, pad), (0, 0))) if pad else gates)
    gg = gg.reshape(G, S, E)
    C = expert_capacity(S, top_k, capacity_factor, E)

    act = get_activation(activation)
    if (dispatch or DISPATCH) == "einsum":
        # float routing metadata end to end: exclusive prefix counts via
        # a strict-lower-triangular matmul (MXU; exact for counts <= S in
        # the f32 accumulator), masks by arithmetic compare — no s32
        # cumsum/gather bands (see DISPATCH note)
        cdt = xp.dtype
        routed_f = (gg > 0).astype(cdt)                    # [G, S, E]
        tril = jnp.tril(jnp.ones((S, S), cdt), -1)         # t < s
        pos = jnp.einsum("st,gte->gse", tril, routed_f,
                         preferred_element_type=jnp.float32)
        keep_f = routed_f * (pos < C).astype(cdt)          # [G, S, E]
        slots = jnp.arange(C, dtype=jnp.float32)
        disp = (keep_f[..., None]
                * (pos[..., None] == slots).astype(cdt))   # [G, S, E, C]
        combine = disp * gg[..., None].astype(cdt)
        xg = xp.reshape(G, S, D)
        expert_in = jnp.einsum("gsec,gsd->egcd", disp, xg)  # [E, G, C, D]
        h = act(jnp.einsum("egcd,edh->egch", expert_in, params["We1"])
                + params["be1"][:, None, None, :])
        out = (jnp.einsum("egch,eho->egco", h, params["We2"])
               + params["be2"][:, None, None, :])
        y = jnp.einsum("gsec,egco->gso", combine, out).reshape(G * S, O)
        y = y[:N] if pad else y
        return (y, aux) if return_aux else y

    # ---- gather dispatch ----
    routed = gg > 0                                        # [G, S, E]
    pos = jnp.cumsum(routed.astype(jnp.int32), axis=1) - 1  # slot per expert
    keep = routed & (pos < C)
    # per-(token, k): its expert id, whether it won a slot, and which
    if pad:
        top_idx = jnp.pad(top_idx, ((0, pad), (0, 0)))
        top_probs = jnp.pad(top_probs, ((0, pad), (0, 0)))
    e_k = top_idx.reshape(G, S, top_k)                     # [G, S, k]
    kept_k = jnp.take_along_axis(keep, e_k, axis=2)        # [G, S, k]
    slot_k = jnp.take_along_axis(pos, e_k, axis=2)         # [G, S, k]
    prob_k = top_probs.reshape(G, S, top_k).astype(xp.dtype)

    # inverse map (e, c) -> source token s, built by scatter; slot C-or-
    # greater (capacity overflow) and sentinel writes drop out of range
    g_idx = jax.lax.broadcasted_iota(jnp.int32, (G, S, top_k), 0)
    s_idx = jax.lax.broadcasted_iota(jnp.int32, (G, S, top_k), 1)
    slot_w = jnp.where(kept_k, slot_k, C)                  # C -> dropped
    idx_buf = jnp.full((G, E, C), S, jnp.int32)            # S -> zero row
    idx_buf = idx_buf.at[g_idx, e_k, slot_w].set(s_idx, mode="drop")

    xg_pad = jnp.pad(xp.reshape(G, S, D), ((0, 0), (0, 1), (0, 0)))
    expert_in = jnp.take_along_axis(
        xg_pad, idx_buf.reshape(G, E * C, 1), axis=1)      # [G, E*C, D]
    expert_in = jnp.moveaxis(
        expert_in.reshape(G, E, C, D), 1, 0)               # [E, G, C, D]
    h = act(jnp.einsum("egcd,edh->egch", expert_in, params["We1"])
            + params["be1"][:, None, None, :])
    out = (jnp.einsum("egch,eho->egco", h, params["We2"])
           + params["be2"][:, None, None, :])              # [E, G, C, O]

    # combine: each token gathers its k slot outputs; dropped (e, slot)
    # pairs point at the padded zero row E*C
    out_pad = jnp.pad(jnp.moveaxis(out, 0, 1).reshape(G, E * C, O),
                      ((0, 0), (0, 1), (0, 0)))            # [G, E*C+1, O]
    flat = jnp.where(kept_k, e_k * C + slot_k, E * C)      # [G, S, k]
    picked = jnp.take_along_axis(
        out_pad, flat.reshape(G, S * top_k, 1), axis=1
    ).reshape(G, S, top_k, O)
    y = jnp.einsum("gsk,gsko->gso", prob_k, picked).reshape(G * S, O)
    y = y[:N] if pad else y
    return (y, aux) if return_aux else y


@register_impl(MixtureOfExpertsLayer)
class MixtureOfExpertsImpl(LayerImpl):
    def init(self, conf, rng, dtype):
        E = conf.n_experts
        D, O = conf.n_in, conf.n_out or conf.n_in
        H = conf.d_hidden or 4 * D
        kg, k1, k2 = jax.random.split(rng, 3)
        We1 = jnp.stack([
            init_weights(k, (D, H), conf.weight_init, conf.dist, dtype)
            for k in jax.random.split(k1, E)])
        We2 = jnp.stack([
            init_weights(k, (H, O), conf.weight_init, conf.dist, dtype)
            for k in jax.random.split(k2, E)])
        return {
            "Wg": init_weights(kg, (D, E), conf.weight_init, conf.dist, dtype),
            "We1": We1, "be1": jnp.zeros((E, H), dtype),
            "We2": We2, "be2": jnp.zeros((E, O), dtype),
        }, {}

    def apply(self, conf, params, state, x, *, train=False, rng=None,
              mask=None):
        if conf.dropout:
            x = apply_dropout(x, conf.dropout, rng, train=train)
        shape = x.shape
        x2d = x.reshape(-1, shape[-1])
        new_state = {k: v for k, v in state.items() if k != AUX_LOSS_KEY}
        if conf.routing == "dense":
            y = moe_apply_dense(params, x2d, top_k=conf.top_k,
                                activation=conf.activation or "gelu")
        else:
            want_aux = train and conf.router_aux_weight > 0
            out = moe_apply_routed(
                params, x2d, top_k=conf.top_k,
                capacity_factor=conf.capacity_factor,
                activation=conf.activation or "gelu",
                group_size=conf.router_group_size, return_aux=want_aux)
            if want_aux:
                y, aux = out
                new_state[AUX_LOSS_KEY] = conf.router_aux_weight * aux
            else:
                y = out
        y = y.reshape(*shape[:-1], y.shape[-1])
        return y, new_state
