"""Convolutional layer implementations: Conv2D, Subsampling (pooling),
BatchNormalization, LocalResponseNormalization.

Reference: layers/convolution/ConvolutionLayer.java (im2col→gemm :120-151),
subsampling/SubsamplingLayer.java, normalization/BatchNormalization.java
(:96-205), normalization/LocalResponseNormalization.java.

TPU-first: NHWC layout; conv is one `lax.conv_general_dilated` (XLA maps it
onto the MXU directly — no im2col materialization); pooling is
`lax.reduce_window`. Backward via jax.grad.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.enums import ConvolutionMode, PoolingType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    ConvolutionLayer,
    LocalResponseNormalization,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.base import LayerImpl, apply_dropout, register_impl
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import get_activation

_DIMS = ("NHWC", "HWIO", "NHWC")


def _padding(conf):
    mode = conf.convolution_mode
    if mode in (ConvolutionMode.SAME, "same"):
        return "SAME"
    if mode in (ConvolutionMode.VALID, "valid"):
        return "VALID"
    p = conf.padding
    return [(int(p[0]), int(p[0])), (int(p[1]), int(p[1]))]


@register_impl(ConvolutionLayer)
class ConvolutionImpl(LayerImpl):
    def init(self, conf, rng, dtype):
        kh, kw = conf.kernel_size
        shape = (int(kh), int(kw), conf.n_in, conf.n_out)
        fan_in = conf.n_in * kh * kw
        fan_out = conf.n_out * kh * kw
        W = init_weights(rng, shape, conf.weight_init, conf.dist, dtype,
                         fan_in=fan_in, fan_out=fan_out)
        b = jnp.full((conf.n_out,), conf.bias_init or 0.0, dtype)
        return {"W": W, "b": b}, {}

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        if conf.dropout:
            x = apply_dropout(x, conf.dropout, rng, train=train)
        # Keep operand/output dtypes uniform: a preferred_element_type that
        # widens bf16->f32 breaks the conv *transpose* rule under jax.grad
        # (f32 cotangent vs bf16 kernel). The TPU MXU accumulates bf16 convs
        # in f32 internally regardless, so uniform bf16 loses nothing.
        z = lax.conv_general_dilated(
            x, params["W"].astype(x.dtype),
            window_strides=tuple(int(s) for s in conf.stride),
            padding=_padding(conf),
            rhs_dilation=tuple(int(d) for d in conf.dilation),
            dimension_numbers=_DIMS,
        )
        z = z + params["b"].astype(z.dtype)
        return get_activation(conf.activation)(z), state


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _maxpool_tiled(x, kh, kw):
    """Non-overlapping max pool (stride == kernel, dims divisible).

    XLA differentiates reduce_window-max through select_and_scatter, which
    ran at ~0.36 ms/step in the VGG-16 trace (r5) — an order of magnitude
    over the HBM cost of the tensors involved. For the tiled case the
    backward is an equality mask: dx = (x == y↑) · dy↑/ties, where ↑ is
    the kh×kw tile upsample and `ties` the per-window count of maxima
    (gradient mass is split across ties; select_and_scatter credits the
    first — both are valid subgradients, identical when the max is
    unique)."""
    kh, kw = int(kh), int(kw)
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, kh, kw, 1),
                             (1, kh, kw, 1), "VALID")


def _maxpool_tiled_fwd(x, kh, kw):
    y = _maxpool_tiled(x, kh, kw)
    return y, (x, y)


def _maxpool_tiled_bwd(kh, kw, res, dy):
    x, y = res
    up = jnp.repeat(jnp.repeat(y, kh, axis=1), kw, axis=2)
    eq = (x == up).astype(dy.dtype)
    ties = lax.reduce_window(eq, 0.0, lax.add, (1, kh, kw, 1),
                             (1, kh, kw, 1), "VALID")
    scaled = jnp.repeat(jnp.repeat(dy / ties, kh, axis=1), kw, axis=2)
    return (eq * scaled,)


_maxpool_tiled.defvjp(_maxpool_tiled_fwd, _maxpool_tiled_bwd)


@register_impl(SubsamplingLayer)
class SubsamplingImpl(LayerImpl):
    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        kh, kw = (int(k) for k in conf.kernel_size)
        sh, sw = (int(s) for s in conf.stride)
        pad = _padding(conf)
        if isinstance(pad, list):
            pad4 = [(0, 0), pad[0], pad[1], (0, 0)]
        else:
            pad4 = pad
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        pt = conf.pooling_type
        if pt in (PoolingType.MAX, "max"):
            zero_pad = (not isinstance(pad, list)
                        or all(p == (0, 0) for p in pad))
            if (zero_pad and sh == kh and sw == kw
                    and x.shape[1] % kh == 0 and x.shape[2] % kw == 0):
                # tiled (non-overlapping, exactly-dividing) pooling: SAME
                # and VALID coincide (zero padding), and the custom
                # equality-mask backward replaces select_and_scatter
                return _maxpool_tiled(x, kh, kw), state
            return (
                lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pad4),
                state,
            )
        if pt in (PoolingType.SUM, "sum"):
            return lax.reduce_window(x, 0.0, lax.add, window, strides, pad4), state
        if pt in (PoolingType.AVG, "avg"):
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pad4)
            ones = jnp.ones_like(x)
            denom = lax.reduce_window(ones, 0.0, lax.add, window, strides, pad4)
            return s / denom, state
        if pt in (PoolingType.PNORM, "pnorm"):
            p = float(conf.pnorm)
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, pad4)
            return s ** (1.0 / p), state
        if pt in (PoolingType.NONE, "none"):
            return x, state
        raise ValueError(f"pooling type {pt}")


@register_impl(BatchNormalization)
class BatchNormImpl(LayerImpl):
    """Train: normalize by batch stats, update running stats in `state`
    (reference :191-197). Eval: use running stats. For NHWC input the stats
    are per-channel; for 2-D input per-feature."""

    def init(self, conf, rng, dtype):
        n = conf.n_out or conf.n_in
        params = {}
        if not conf.lock_gamma_beta:
            params["gamma"] = jnp.full((n,), conf.gamma, dtype)
            params["beta"] = jnp.full((n,), conf.beta, dtype)
        state = {"mean": jnp.zeros((n,), jnp.float32), "var": jnp.ones((n,), jnp.float32),
                 "count": jnp.zeros((), jnp.float32)}
        return params, state

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        axes = tuple(range(x.ndim - 1))  # all but channel/feature
        if train:
            # at least f32 for the stats, but never truncate wider inputs
            # (f64 gradient checks rely on exact mean cancellation)
            stat_dtype = jnp.promote_types(x.dtype, jnp.float32)
            xf = x.astype(stat_dtype)
            if x.dtype == jnp.bfloat16:
                # one-pass stats: sum and sum-of-squares reduce in a single
                # read of the activation (XLA multi-output fusion) — the
                # two-pass mean-then-var formulation re-read every BN input
                # twice and was ~40% of the VGG-16 step (r5 trace). Only
                # for bf16 compute (the TPU perf path): E[x^2]-mean^2 in
                # the f32 accumulator is exact enough there (bf16 data has
                # ~3 significant digits; mean^2/var would need to exceed
                # 2^24 to cancel), while f32/f64 inputs keep the
                # cancellation-exact mean-then-var form below.
                n = x.size // x.shape[-1]
                mean = jnp.sum(xf, axis=axes) / n
                var = jnp.maximum(
                    jnp.sum(xf * xf, axis=axes) / n - mean * mean, 0.0)
            else:
                mean = jnp.mean(xf, axis=axes)
                var = jnp.var(xf, axis=axes)
            decay = conf.decay
            new_state = {
                "mean": (decay * state["mean"] + (1 - decay) * mean).astype(
                    state["mean"].dtype),
                "var": (decay * state["var"] + (1 - decay) * var).astype(
                    state["var"].dtype),
                "count": state["count"] + 1,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        xn = (x - mean.astype(x.dtype)) * lax.rsqrt(var + conf.eps).astype(x.dtype)
        if "gamma" in params:
            xn = xn * params["gamma"] + params["beta"]
        return get_activation(conf.activation or "identity")(xn), new_state


@register_impl(LocalResponseNormalization)
class LRNImpl(LayerImpl):
    """Cross-channel LRN on NHWC: y = x / (k + alpha*sum_local x^2)^beta."""

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        n = int(conf.n)
        half = n // 2
        sq = x * x
        # sum over a window of `n` adjacent channels via reduce_window on last axis
        window = (1,) * (x.ndim - 1) + (n,)
        strides = (1,) * x.ndim
        pad = [(0, 0)] * (x.ndim - 1) + [(half, n - 1 - half)]
        ssum = lax.reduce_window(sq, 0.0, lax.add, window, strides, pad)
        denom = (conf.k + conf.alpha * ssum) ** conf.beta
        return x / denom, state
