"""Layer implementations. Importing this package registers every impl."""

from deeplearning4j_tpu.nn.layers.base import (  # noqa: F401
    LayerImpl,
    apply_dropout,
    get_impl,
    l1_l2_penalty,
    register_impl,
)
import deeplearning4j_tpu.nn.layers.feedforward  # noqa: F401
import deeplearning4j_tpu.nn.layers.convolution  # noqa: F401
import deeplearning4j_tpu.nn.layers.recurrent  # noqa: F401
import deeplearning4j_tpu.nn.layers.attention  # noqa: F401
import deeplearning4j_tpu.nn.layers.moe  # noqa: F401
import deeplearning4j_tpu.nn.layers.nested  # noqa: F401
