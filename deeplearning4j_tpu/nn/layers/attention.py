"""Transformer building blocks: LayerNormalization, SelfAttentionLayer.

New capabilities for the Transformer north star (SURVEY.md §7 step 6) — no
reference analogue. Attention computes per-head scaled dot product over
[batch, time, features]; XLA fuses the softmax chain. A ring-attention
sequence-parallel variant lives in deeplearning4j_tpu/parallel/ring_attention.py
and is selected by the parallel plan, not the layer config.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.flash_attention import (
    MAX_FLASH_T,
    chunked_flash_attention,
    chunked_unsupported_reason,
    flash_attention,
    flash_attention_qkv,
    supports as flash_supports,
    supports_chunked as flash_supports_chunked,
    supports_monolithic_fallback as flash_supports_monolithic_fallback,
    supports_qkv as flash_supports_qkv,
)
from deeplearning4j_tpu.nn.conf.layers import (
    LayerNormalization,
    PositionalEncodingLayer,
    SelfAttentionLayer,
)
from deeplearning4j_tpu.nn.layers.base import LayerImpl, apply_dropout, register_impl
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import get_activation


@register_impl(LayerNormalization)
class LayerNormImpl(LayerImpl):
    def init(self, conf, rng, dtype):
        n = conf.n_out or conf.n_in
        return {"gamma": jnp.ones((n,), dtype), "beta": jnp.zeros((n,), dtype)}, {}

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        # Deliberately the plain jnp form: a Pallas fused LN exists
        # (ops/fused_layernorm.py) but LOST a same-window A/B on v5e
        # (0.494 MFU with XLA's lowering vs 0.455 fused at the flagship
        # shapes) — XLA fuses the normalize into neighboring residual/
        # matmul fusions, which a pallas_call boundary forbids. Kept as
        # an op for shapes where that tradeoff flips.
        # (r5: an E[x^2]-mu^2 one-pass variant — the trick that cut the
        # VGG BatchNorm's spatial reductions 30x — A/B'd FLAT here:
        # 1.97M vs 1.99M tok/s interleaved means. XLA already multi-
        # output-fuses LN's lane-axis mean+var into one read at these
        # shapes, so the rewrite only traded numerics for nothing.)
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        xn = (x - mu) * jax.lax.rsqrt(var + conf.eps)
        return xn * params["gamma"] + params["beta"], state


def _sp_axis_in_scope(name: str) -> bool:
    """True when `name` is a bound mesh axis (i.e. we are tracing inside
    the sequence-parallel shard_map). An SP-configured layer used OUTSIDE
    shard_map — ordinary inference after SP training, a reloaded config —
    falls back to the dense path, which is the correct full-sequence
    semantics on one host."""
    if not name:
        return False
    try:
        jax.lax.axis_index(name)  # unused op when bound; DCE'd
        return True
    except NameError:
        return False


@register_impl(PositionalEncodingLayer)
class PositionalEncodingImpl(LayerImpl):
    def init(self, conf, rng, dtype):
        if conf.learned:
            pe = 0.02 * jax.random.normal(
                rng, (conf.max_length, conf.n_features), dtype)
            return {"pe": pe}, {}
        return {}, {}

    @staticmethod
    def _sinusoidal(T, d, dtype, offset=0):
        pos = (offset + jnp.arange(T))[:, None].astype(jnp.float32)
        dim = jnp.arange(0, d, 2).astype(jnp.float32)
        angle = pos / jnp.power(10000.0, dim / d)
        pe = jnp.zeros((T, d), jnp.float32)
        pe = pe.at[:, 0::2].set(jnp.sin(angle))
        pe = pe.at[:, 1::2].set(jnp.cos(angle[:, : d // 2]))
        return pe.astype(dtype)

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        T, d = x.shape[1], x.shape[2]
        offset = 0
        axis = getattr(conf, "seq_parallel_axis", "")
        if _sp_axis_in_scope(axis):
            # inside the sequence-parallel shard_map: x is the LOCAL block
            # of the sequence — encode its global positions
            if conf.learned:
                # psum of a Python scalar is the static axis size; check at
                # trace time (dynamic_slice would silently CLAMP an
                # overflowing offset, duplicating pe rows across shards)
                n_shards = jax.lax.psum(1, axis)
                if n_shards * T > conf.max_length:
                    raise ValueError(
                        f"global sequence {n_shards}x{T} exceeds learned "
                        f"positional table max_length={conf.max_length}")
            offset = jax.lax.axis_index(axis) * T
        if conf.learned:
            pe = jax.lax.dynamic_slice(params["pe"], (offset, 0), (T, d))
        else:
            pe = self._sinusoidal(T, d, x.dtype, offset)
        return x + pe, state


def dot_product_attention(q, k, v, *, causal, mask=None, dropout=0.0, rng=None,
                          train=False):
    """q,k,v: [B, H, T, D]. Returns [B, H, T, D]. Computed in f32 for the
    softmax (bf16-safe), outputs cast back to q.dtype."""
    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / jnp.sqrt(float(d))
    T = q.shape[2]
    if causal:
        cm = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(cm, scores, -1e30)
    if mask is not None:
        # mask: [B, T] keyed on keys
        scores = jnp.where(mask[:, None, None, :].astype(bool), scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    if dropout and train and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - dropout, w.shape)
        w = jnp.where(keep, w / (1.0 - dropout), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", w.astype(v.dtype), v)
    return out.astype(q.dtype)


@register_impl(SelfAttentionLayer)
class SelfAttentionImpl(LayerImpl):
    def init(self, conf, rng, dtype):
        k1, k2 = jax.random.split(rng)
        n_in, n = conf.n_in, conf.n_out
        return {
            "Wqkv": init_weights(k1, (n_in, 3 * n), conf.weight_init, conf.dist,
                                 dtype, fan_in=n_in, fan_out=n),
            "bqkv": jnp.zeros((3 * n,), dtype),
            "Wo": init_weights(k2, (n, n), conf.weight_init, conf.dist, dtype),
            "bo": jnp.zeros((n,), dtype),
        }, {}

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        if conf.dropout:
            rng, sub = jax.random.split(rng) if rng is not None else (None, None)
            x = apply_dropout(x, conf.dropout, sub, train=train)
        B, T, _ = x.shape
        H = conf.n_heads
        n = conf.n_out
        D = n // H
        qkv = x @ params["Wqkv"] + params["bqkv"]  # [B, T, 3n]
        drop_attn = conf.attention_dropout if train else 0.0
        use_flash = getattr(conf, "use_flash", True)
        if (use_flash
                and not _sp_axis_in_scope(getattr(conf, "seq_parallel_axis",
                                                  ""))
                and flash_supports_qkv(B, T, n, H, dropout=drop_attn)):
            # packed path: the kernels read head column-slices straight
            # from the projection output — no [B,T,H,D]->[B,H,T,D]
            # relayout in either direction (r4 MFU item a). Attention
            # dropout stays on this path too (r5): the r4 fallback to the
            # flat layout re-paid ~0.9 ms/step of head transposes, most
            # of the VERDICT r4 #2 dropout MFU tax
            out = flash_attention_qkv(qkv, H, causal=conf.causal, mask=mask,
                                      dropout=drop_attn, dropout_rng=rng)
            y = out @ params["Wo"] + params["bo"]
            return get_activation(conf.activation or "identity")(y), state
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, T, H, D).transpose(0, 2, 1, 3)

        qh, kh, vh = heads(q), heads(k), heads(v)
        if _sp_axis_in_scope(getattr(conf, "seq_parallel_axis", "")):
            # inside the sequence-parallel shard_map: local q block attends
            # the K/V blocks rotating around the ICI ring; the full [T, T]
            # scores never exist on any one shard. Attention dropout rides
            # the ring since r6 (global-coordinate keep mask; the step rng
            # is replicated across seq shards, which is exactly what the
            # mask needs)
            if mask is not None:
                raise ValueError(
                    "sequence-parallel attention does not support padding "
                    "masks — pad to full length")
            from deeplearning4j_tpu.parallel.ring_attention import (
                ring_attention,
            )

            out = ring_attention(qh, kh, vh,
                                 axis_name=conf.seq_parallel_axis,
                                 causal=conf.causal,
                                 dropout=drop_attn, dropout_rng=rng)
        elif use_flash and flash_supports(
                qh.shape, causal=conf.causal, dropout=drop_attn, mask=mask):
            out = flash_attention(qh, kh, vh, causal=conf.causal, mask=mask,
                                  dropout=drop_attn, dropout_rng=rng)
        elif use_flash and flash_supports_chunked(
                qh.shape, causal=conf.causal, dropout=drop_attn, mask=mask):
            # T beyond the monolithic kernels' envelope: blockwise
            # tiles + lse merge (single-chip ring); padding masks slice
            # per kv tile and dropout hashes global coordinates (r6), so
            # the full training feature set rides this path. Since r8
            # the tier is D-aware (head dims past 128 use shorter proven
            # tiles) and non-causal kv tiles scan instead of unrolling
            # n^2 kernel calls. Past this, the seq mesh axis shards T
            # across chips (sequence_parallel.py)
            out = chunked_flash_attention(qh, kh, vh, causal=conf.causal,
                                          mask=mask, dropout=drop_attn,
                                          dropout_rng=rng)
        elif (use_flash and T > MAX_FLASH_T
              and flash_supports_monolithic_fallback(
                  qh.shape, causal=conf.causal, dropout=drop_attn,
                  mask=mask)):
            # non-tileable T at D <= 128 still compiles monolithically
            # to MONOLITHIC_COMPILE_MAX (every in-kernel feature rides)
            out = flash_attention(qh, kh, vh, causal=conf.causal, mask=mask,
                                  dropout=drop_attn, dropout_rng=rng)
        elif use_flash and T > MAX_FLASH_T:
            # dense [T, T] scores at these lengths are a guaranteed
            # device OOM — fail with instructions, not an opaque OOM
            raise ValueError(chunked_unsupported_reason(
                T, dropout=drop_attn, mask=mask, causal=conf.causal,
                head_dim=D))
        else:
            out = dot_product_attention(
                qh, kh, vh, causal=conf.causal, mask=mask,
                dropout=conf.attention_dropout, rng=rng, train=train,
            )
        out = out.transpose(0, 2, 1, 3).reshape(B, T, n)
        y = out @ params["Wo"] + params["bo"]
        return get_activation(conf.activation or "identity")(y), state
