"""Feed-forward layer implementations: Dense, Output, Activation, Dropout,
Embedding, AutoEncoder, RBM.

Reference impls: layers/feedforward/dense/DenseLayer.java (via BaseLayer.java
preOutput `input.mmul(W).addiRowVector(b)`:361), embedding/EmbeddingLayer.java,
autoencoder/AutoEncoder.java, rbm/RBM.java (contrastiveDivergence:101).
All backward passes come from jax.grad.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.enums import HiddenUnit, VisibleUnit
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    AutoEncoder,
    BaseOutputLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    RBM,
)
from deeplearning4j_tpu.nn.layers.base import (
    LayerImpl,
    apply_dropconnect,
    apply_dropout,
    register_impl,
)
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.losses import compute_loss


def _dense_init(conf, rng, dtype):
    kW, _ = jax.random.split(rng)
    W = init_weights(kW, (conf.n_in, conf.n_out), conf.weight_init, conf.dist, dtype)
    b = jnp.full((conf.n_out,), conf.bias_init or 0.0, dtype)
    return {"W": W, "b": b}, {}


def _dense_forward(conf, params, x, train, rng):
    W = params["W"]
    if getattr(conf, "drop_connect", False):
        W = apply_dropconnect(W, conf.dropout, rng, train=train)
    elif conf.dropout:
        x = apply_dropout(x, conf.dropout, rng, train=train)
    z = x @ W + params["b"]
    return get_activation(conf.activation)(z), z


@register_impl(DenseLayer)
class DenseImpl(LayerImpl):
    def init(self, conf, rng, dtype):
        return _dense_init(conf, rng, dtype)

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        y, _ = _dense_forward(conf, params, x, train, rng)
        return y, state


@register_impl(BaseOutputLayer)
class OutputImpl(LayerImpl):
    """Output layer: dense + activation; the container computes the loss on
    the preactivation for numeric stability (reference BaseOutputLayer
    computes the softmax/loss delta jointly)."""

    def init(self, conf, rng, dtype):
        return _dense_init(conf, rng, dtype)

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        y, z = _dense_forward(conf, params, x, train, rng)
        return y, state

    def preactivation(self, conf, params, x, *, train=False, rng=None):
        _, z = _dense_forward(conf, params, x, train, rng)
        return z

    def loss(self, conf, params, x, labels, *, train=False, rng=None,
             mask=None, per_example=False):
        """Scalar training loss; ``per_example=True`` returns one score per
        example [B] instead (reference ScoreExamplesFunction semantics)."""
        act = (conf.activation or "").lower()
        if self._use_fused_head(conf, params, x, labels, act):
            from deeplearning4j_tpu.ops.fused_softmax_xent import (
                softmax_xent_head,
            )
            from deeplearning4j_tpu.ops.losses import _finish

            if conf.dropout:
                x = apply_dropout(x, conf.dropout, rng, train=train)
            per = softmax_xent_head(x, params["W"], params["b"], labels)
            return _finish(per, mask, not per_example)
        y, z = _dense_forward(conf, params, x, train, rng)
        logits = z if act in ("softmax", "sigmoid") else None
        return compute_loss(conf.loss_function, labels, y, mask,
                            logits=logits, reduce=not per_example)

    @staticmethod
    def _use_fused_head(conf, params, x, labels, act):
        """Large-vocab sparse-label softmax/mcxent on TPU: dispatch to the
        fused Pallas head (ops/fused_softmax_xent.py) instead of
        materializing [N, V] logits."""
        from deeplearning4j_tpu.ops import fused_softmax_xent as fsx
        from deeplearning4j_tpu.ops.losses import LossFunction

        if fsx.FORCE_FUSED is False:
            return False
        loss_name = conf.loss_function
        if callable(loss_name):
            return False
        if act != "softmax" or str(loss_name).lower() not in (
                LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD):
            return False
        if not (labels.ndim == x.ndim - 1
                and jnp.issubdtype(labels.dtype, jnp.integer)):
            return False
        if getattr(conf, "drop_connect", False):
            return False
        n = int(np.prod(x.shape[:-1]))
        d = x.shape[-1]
        v = params["W"].shape[-1]
        if not fsx.supports(n, d, v):
            return False
        return bool(fsx.FORCE_FUSED) or jax.default_backend() == "tpu"


@register_impl(ActivationLayer)
class ActivationImpl(LayerImpl):
    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        if conf.dropout:
            x = apply_dropout(x, conf.dropout, rng, train=train)
        return get_activation(conf.activation)(x), state


@register_impl(DropoutLayer)
class DropoutImpl(LayerImpl):
    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        return apply_dropout(x, conf.dropout, rng, train=train), state


@register_impl(EmbeddingLayer)
class EmbeddingImpl(LayerImpl):
    """Index lookup. The reference implements this as a select of rows of W
    (EmbeddingLayer.java); here it is jnp.take — XLA lowers it to a dynamic
    gather; grads are scatter-adds. Input: int [batch] or [batch, 1]."""

    def init(self, conf, rng, dtype):
        params, _ = _dense_init(conf, rng, dtype)
        if not conf.has_bias:
            params.pop("b")
        return params, {}

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        z = jnp.take(params["W"], idx, axis=0)
        if "b" in params:
            z = z + params["b"]
        return get_activation(conf.activation)(z), state


@register_impl(AutoEncoder)
class AutoEncoderImpl(LayerImpl):
    """Denoising autoencoder with tied decode weights W^T (reference
    AutoEncoder.java: encode/decode with corruption; pretrain minimizes
    reconstruction loss; as a frozen feed-forward layer it encodes)."""

    def init(self, conf, rng, dtype):
        params, _ = _dense_init(conf, rng, dtype)
        params["vb"] = jnp.full((conf.n_in,), conf.visible_bias_init, dtype)
        return params, {}

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        act = get_activation(conf.activation)
        return act(x @ params["W"] + params["b"]), state

    def encode(self, conf, params, x):
        return get_activation(conf.activation)(x @ params["W"] + params["b"])

    def decode(self, conf, params, h):
        return get_activation(conf.activation)(h @ params["W"].T + params["vb"])

    def pretrain_loss(self, conf, params, x, rng):
        corrupted = x
        if conf.corruption_level and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - conf.corruption_level, x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        h = self.encode(conf, params, corrupted)
        recon = self.decode(conf, params, h)
        loss = compute_loss(conf.loss_function, x, recon)
        if conf.sparsity:
            rho_hat = jnp.clip(jnp.mean(h, axis=0), 1e-6, 1 - 1e-6)
            rho = conf.sparsity
            loss = loss + jnp.sum(
                rho * jnp.log(rho / rho_hat)
                + (1 - rho) * jnp.log((1 - rho) / (1 - rho_hat))
            )
        return loss


@register_impl(RBM)
class RBMImpl(LayerImpl):
    """RBM trained by CD-k with keyed PRNG sampling inside jit (reference
    RBM.java contrastiveDivergence:101, Gibbs chain gibbhVh:149-151, unit
    types :197-205). The CD-k gradient is expressed as a surrogate loss
    (free-energy difference) whose jax.grad equals the CD update — keeping
    the no-hand-written-gradients invariant.
    """

    def init(self, conf, rng, dtype):
        params, _ = _dense_init(conf, rng, dtype)
        params["vb"] = jnp.full((conf.n_in,), conf.visible_bias_init, dtype)
        return params, {}

    def apply(self, conf, params, state, x, *, train=False, rng=None, mask=None):
        # as a stacked feed-forward layer: hidden mean activation
        h, _ = self._prop_up(conf, params, x)
        return h, state

    def _prop_up(self, conf, params, v):
        z = v @ params["W"] + params["b"]
        hu = conf.hidden_unit
        if hu == HiddenUnit.BINARY:
            return jax.nn.sigmoid(z), z
        if hu == HiddenUnit.RECTIFIED:
            return jax.nn.relu(z), z
        if hu == HiddenUnit.GAUSSIAN:
            return z, z
        if hu == HiddenUnit.SOFTMAX:
            return jax.nn.softmax(z, axis=-1), z
        raise ValueError(f"hidden unit {hu}")

    def _prop_down(self, conf, params, h):
        z = h @ params["W"].T + params["vb"]
        vu = conf.visible_unit
        if vu == VisibleUnit.BINARY:
            return jax.nn.sigmoid(z), z
        if vu in (VisibleUnit.GAUSSIAN, VisibleUnit.LINEAR):
            return z, z
        if vu == VisibleUnit.SOFTMAX:
            return jax.nn.softmax(z, axis=-1), z
        raise ValueError(f"visible unit {vu}")

    def _sample_h(self, conf, params, v, rng):
        mean, _ = self._prop_up(conf, params, v)
        if conf.hidden_unit == HiddenUnit.BINARY:
            return jax.random.bernoulli(rng, mean).astype(mean.dtype), mean
        if conf.hidden_unit == HiddenUnit.GAUSSIAN:
            return mean + jax.random.normal(rng, mean.shape, mean.dtype), mean
        return mean, mean  # rectified/softmax: mean-field

    def _sample_v(self, conf, params, h, rng):
        mean, _ = self._prop_down(conf, params, h)
        if conf.visible_unit == VisibleUnit.BINARY:
            return jax.random.bernoulli(rng, mean).astype(mean.dtype), mean
        if conf.visible_unit == VisibleUnit.GAUSSIAN:
            return mean + jax.random.normal(rng, mean.shape, mean.dtype), mean
        return mean, mean

    def free_energy(self, conf, params, v):
        """F(v) = -v·vb - sum softplus(vW+b) (binary hidden)."""
        z = v @ params["W"] + params["b"]
        fe = -(v @ params["vb"]) - jnp.sum(jax.nn.softplus(z), axis=-1)
        if conf.visible_unit == VisibleUnit.GAUSSIAN:
            fe = fe + 0.5 * jnp.sum(v * v, axis=-1)
        return fe

    def pretrain_loss(self, conf, params, x, rng):
        """CD-k surrogate: mean F(v_data) - F(v_model), with the negative
        sample treated as a constant (stop_gradient) — grad of this equals
        the CD-k update."""
        k = max(1, conf.k)
        keys = jax.random.split(rng, 2 * k)
        v = x
        for i in range(k):
            h, _ = self._sample_h(conf, params, v, keys[2 * i])
            v, _ = self._sample_v(conf, params, h, keys[2 * i + 1])
        v_neg = jax.lax.stop_gradient(v)
        return jnp.mean(self.free_energy(conf, params, x) - self.free_energy(conf, params, v_neg))
