"""In-memory adjacency-list graph (reference: graph/graph/Graph.java —
addEdge, getVertexDegree, getConnectedVertexIndices, getEdgesOut).

Adjacency is stored as per-vertex NumPy arrays (neighbour indices +
weights) so walk generation samples with vectorised RNG calls rather than
per-edge object traversal.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

from .api import Edge, Vertex


class Graph:
    """Adjacency-list graph over `num_vertices` integer-indexed vertices."""

    def __init__(self, num_vertices: int, allow_multiple_edges: bool = True,
                 vertices: Optional[Sequence[Vertex]] = None):
        if num_vertices <= 0:
            raise ValueError("num_vertices must be positive")
        self.num_vertices_ = int(num_vertices)
        self.allow_multiple_edges = allow_multiple_edges
        self.vertices: List[Vertex] = (
            list(vertices) if vertices is not None
            else [Vertex(i) for i in range(num_vertices)])
        if len(self.vertices) != num_vertices:
            raise ValueError("vertices length mismatch")
        self._adj: List[List[int]] = [[] for _ in range(num_vertices)]
        self._w: List[List[float]] = [[] for _ in range(num_vertices)]
        self._edges: List[Edge] = []
        self._frozen_adj: Optional[List[np.ndarray]] = None
        self._frozen_w: Optional[List[np.ndarray]] = None

    # ------------------------------------------------------------ mutation
    def add_edge(self, edge_or_src, dst: Optional[int] = None,
                 weight: float = 1.0, directed: bool = False) -> None:
        e = (edge_or_src if isinstance(edge_or_src, Edge)
             else Edge(int(edge_or_src), int(dst), weight, directed))
        for v in (e.src, e.dst):
            if not (0 <= v < self.num_vertices_):
                raise ValueError(f"vertex index {v} out of range")
        if not self.allow_multiple_edges and e.dst in self._adj[e.src]:
            return
        self._edges.append(e)
        self._adj[e.src].append(e.dst)
        self._w[e.src].append(e.weight)
        if not e.directed and e.src != e.dst:
            self._adj[e.dst].append(e.src)
            self._w[e.dst].append(e.weight)
        self._frozen_adj = self._frozen_w = None

    # ------------------------------------------------------------- queries
    def num_vertices(self) -> int:
        return self.num_vertices_

    def num_edges(self) -> int:
        return len(self._edges)

    def get_vertex(self, idx: int) -> Vertex:
        return self.vertices[idx]

    def get_vertex_degree(self, idx: int) -> int:
        return len(self._adj[idx])

    def get_connected_vertex_indices(self, idx: int) -> np.ndarray:
        self._freeze()
        return self._frozen_adj[idx]

    def get_edge_weights(self, idx: int) -> np.ndarray:
        self._freeze()
        return self._frozen_w[idx]

    def get_edges_out(self, idx: int) -> List[Edge]:
        """Edges leaving `idx`, always oriented src=idx → dst=neighbour
        (undirected edges stored as (a, idx) are returned reoriented)."""
        out = []
        for e in self._edges:
            if e.src == idx:
                out.append(e)
            elif not e.directed and e.dst == idx:
                out.append(Edge(idx, e.src, e.weight, e.directed, e.value))
        return out

    def edges(self) -> Iterable[Edge]:
        return iter(self._edges)

    def degrees(self) -> np.ndarray:
        return np.array([len(a) for a in self._adj])

    def _freeze(self) -> None:
        if self._frozen_adj is None:
            self._frozen_adj = [np.asarray(a, dtype=np.int64) for a in self._adj]
            self._frozen_w = [np.asarray(w, dtype=np.float64) for w in self._w]
