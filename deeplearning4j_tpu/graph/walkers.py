"""Random-walk generation (reference: deeplearning4j-graph
iterator/{RandomWalkIterator, WeightedRandomWalkIterator}.java and
nlp models/sequencevectors/graph/walkers/{RandomWalker, WeightedWalker,
PopularityWalker}).

Walks are produced as int arrays; `walks()` yields them and
`walk_sequences()` yields vertex-id *strings* ready for the
SequenceVectors engine.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from .graph import Graph


class NoEdgeHandling:
    """What to do at a dead-end vertex (reference NoEdgeHandling enum)."""

    SELF_LOOP_ON_DISCONNECTED = "self_loop"
    EXCEPTION_ON_DISCONNECTED = "exception"
    CUTOFF_ON_DISCONNECTED = "cutoff"
    RESTART_ON_DISCONNECTED = "restart"


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex
    (iterator/RandomWalkIterator.java)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 no_edge_handling: str = NoEdgeHandling.EXCEPTION_ON_DISCONNECTED):
        self.graph = graph
        self.walk_length = int(walk_length)
        self.no_edge_handling = no_edge_handling
        self._rng = np.random.default_rng(seed)
        self._position = 0

    def reset(self) -> None:
        self._position = 0

    def has_next(self) -> bool:
        return self._position < self.graph.num_vertices()

    def next(self) -> np.ndarray:
        if not self.has_next():
            raise StopIteration
        start = self._position
        self._position += 1
        return self._walk_from(start)

    def __iter__(self) -> Iterator[np.ndarray]:
        self.reset()
        while self.has_next():
            yield self.next()

    def _choose(self, nbrs: np.ndarray, weights: Optional[np.ndarray]) -> int:
        return int(nbrs[self._rng.integers(len(nbrs))])

    def _walk_from(self, start: int) -> np.ndarray:
        walk = np.empty(self.walk_length + 1, dtype=np.int64)
        walk[0] = start
        cur = start
        for i in range(1, self.walk_length + 1):
            nbrs = self.graph.get_connected_vertex_indices(cur)
            if len(nbrs) == 0:
                mode = self.no_edge_handling
                if mode == NoEdgeHandling.EXCEPTION_ON_DISCONNECTED:
                    raise RuntimeError(
                        f"vertex {cur} has no edges "
                        "(NoEdgeHandling.EXCEPTION_ON_DISCONNECTED)")
                if mode == NoEdgeHandling.CUTOFF_ON_DISCONNECTED:
                    return walk[:i].copy()
                if mode == NoEdgeHandling.RESTART_ON_DISCONNECTED:
                    cur = start
                # SELF_LOOP: stay put
                walk[i] = cur
                continue
            cur = self._choose(nbrs, self.graph.get_edge_weights(cur))
            walk[i] = cur
        return walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional walks (iterator/WeightedRandomWalkIterator.java)."""

    def _choose(self, nbrs: np.ndarray, weights: Optional[np.ndarray]) -> int:
        total = weights.sum()
        if total <= 0:
            return int(nbrs[self._rng.integers(len(nbrs))])
        return int(nbrs[self._rng.choice(len(nbrs), p=weights / total)])


class PopularityWalker(RandomWalkIterator):
    """Degree-biased walks: next hop proportional to neighbour degree
    (nlp sequencevectors/graph/walkers/PopularityWalker.java)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 spread: int = 10, **kw):
        super().__init__(graph, walk_length, seed, **kw)
        self.spread = spread
        self._degrees = graph.degrees().astype(np.float64)

    def _choose(self, nbrs: np.ndarray, weights: Optional[np.ndarray]) -> int:
        cand = nbrs
        if len(cand) > self.spread:
            cand = cand[self._rng.choice(len(cand), self.spread, replace=False)]
        pop = self._degrees[cand]
        total = pop.sum()
        if total <= 0:
            return int(cand[self._rng.integers(len(cand))])
        return int(cand[self._rng.choice(len(cand), p=pop / total)])


def walk_sequences(walker: RandomWalkIterator, walks_per_vertex: int = 1):
    """All walks as vertex-id string sequences for SequenceVectors."""
    out = []
    for _ in range(walks_per_vertex):
        for walk in walker:
            out.append([str(v) for v in walk])
    return out
