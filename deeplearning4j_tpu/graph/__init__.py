"""Graph embeddings (reference: deeplearning4j-graph module, 3,295 LoC —
SURVEY.md §2.5: graph/api/{IGraph,Vertex,Edge}, graph/graph/Graph.java,
data/GraphLoader.java, iterator walkers, models/deepwalk/DeepWalk.java).

Host-side graph storage + walk generation feeding the batched
SequenceVectors engine (walks are just token sequences of vertex ids), so
DeepWalk trains with the same jitted skip-gram device steps as Word2Vec —
the TPU replacement for the reference's per-pair hierarchical-softmax
HogWild updates.
"""

from .api import Edge, Vertex
from .graph import Graph
from .loader import GraphLoader
from .walkers import (
    NoEdgeHandling,
    PopularityWalker,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)
from .deepwalk import DeepWalk, GraphVectorSerializer

__all__ = [
    "Edge",
    "Vertex",
    "Graph",
    "GraphLoader",
    "NoEdgeHandling",
    "RandomWalkIterator",
    "WeightedRandomWalkIterator",
    "PopularityWalker",
    "DeepWalk",
    "GraphVectorSerializer",
]
