"""Graph primitives (reference: deeplearning4j-graph
graph/api/{Vertex, Edge}.java)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional


@dataclass(frozen=True)
class Vertex:
    """A vertex: integer index + optional payload (api/Vertex.java)."""

    idx: int
    value: Any = None


@dataclass(frozen=True)
class Edge:
    """An edge between vertex indices, optionally weighted/directed
    (api/Edge.java)."""

    src: int
    dst: int
    weight: float = 1.0
    directed: bool = False
    value: Optional[Any] = None
