"""Graph loading from edge-list files (reference: deeplearning4j-graph
data/GraphLoader.java + edge/vertex line processors: loadUndirectedGraphEdgeListFile,
loadWeightedEdgeListFile)."""

from __future__ import annotations

from typing import Callable, Optional

from .graph import Graph


class GraphLoader:
    @staticmethod
    def load_undirected_graph_edge_list_file(path: str, num_vertices: int,
                                             delimiter: Optional[str] = None) -> Graph:
        """Each line: `src dst` (GraphLoader.loadUndirectedGraphEdgeListFile)."""
        g = Graph(num_vertices)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delimiter)
                g.add_edge(int(parts[0]), int(parts[1]))
        return g

    @staticmethod
    def load_weighted_edge_list_file(path: str, num_vertices: int,
                                     delimiter: Optional[str] = None,
                                     directed: bool = False) -> Graph:
        """Each line: `src dst weight` (GraphLoader.loadWeightedEdgeListFile)."""
        g = Graph(num_vertices)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delimiter)
                g.add_edge(int(parts[0]), int(parts[1]),
                           weight=float(parts[2]), directed=directed)
        return g

    @staticmethod
    def load_adjacency_list_file(path: str, num_vertices: int,
                                 delimiter: Optional[str] = None) -> Graph:
        """Each line: `v n1 n2 ...` — directed edges v→ni
        (GraphLoader adjacency list variant)."""
        g = Graph(num_vertices)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delimiter)
                v = int(parts[0])
                for n in parts[1:]:
                    g.add_edge(v, int(n), directed=True)
        return g
