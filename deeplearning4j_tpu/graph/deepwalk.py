"""DeepWalk node embeddings (reference: deeplearning4j-graph
models/deepwalk/DeepWalk.java — skip-gram with hierarchical softmax over
random walks, GraphHuffman coding; embeddings/InMemoryGraphLookupTable.java;
GraphVectorSerializer.java).

TPU-native: walks are generated host-side and fed to the SequenceVectors
engine, so training is the same batched, jitted skip-gram device step as
Word2Vec (hierarchical softmax by default, matching the reference) instead
of per-pair BLAS-1 updates.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors

from .graph import Graph
from .walkers import RandomWalkIterator, WeightedRandomWalkIterator, walk_sequences


class DeepWalk:
    """DeepWalk trainer (DeepWalk.java Builder: vectorSize, windowSize,
    learningRate; fit(graph, walkLength))."""

    class Builder:
        def __init__(self):
            self._kw = dict(vector_size=100, window_size=5,
                            learning_rate=0.025, seed=0)

        def vector_size(self, n: int):
            self._kw["vector_size"] = n
            return self

        def window_size(self, n: int):
            self._kw["window_size"] = n
            return self

        def learning_rate(self, lr: float):
            self._kw["learning_rate"] = lr
            return self

        def seed(self, s: int):
            self._kw["seed"] = s
            return self

        def use_engine(self, flag=True, ep: int = 1, dp: int = 1):
            """Sharded-embedding-engine training (on by default); ep/dp
            pick the mesh axes — see Word2Vec.Builder.use_engine."""
            self._kw["use_engine"] = flag
            self._kw["engine_ep"] = int(ep)
            self._kw["engine_dp"] = int(dp)
            return self

        def build(self) -> "DeepWalk":
            return DeepWalk(**self._kw)

    @staticmethod
    def builder() -> "DeepWalk.Builder":
        return DeepWalk.Builder()

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.025, seed: int = 0,
                 use_engine: bool = True, engine_ep: int = 1,
                 engine_dp: int = 1):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.seed = seed
        # DeepWalk is a thin front-end over the sharded embedding
        # engine (embedding/engine.py) — the HS skip-gram step runs the
        # engine's sparse-gather path, bit-identical to legacy at ep=1
        self.use_engine = use_engine
        self.engine_ep = engine_ep
        self.engine_dp = engine_dp
        self.vectors: Optional[SequenceVectors] = None
        self.num_vertices = 0

    def fit(self, graph_or_walker, walk_length: int = 40,
            walks_per_vertex: int = 1, epochs: int = 1,
            weighted: bool = False,
            no_edge_handling: str | None = None) -> "DeepWalk":
        """Generate walks and train (DeepWalk.fit(IGraph, walkLength)).
        Accepts a Graph (builds the walker) or a walk iterator. The walker
        default raises on dead-end vertices (reference parity); pass
        no_edge_handling=NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED for graphs
        with sinks."""
        if isinstance(graph_or_walker, Graph):
            cls = WeightedRandomWalkIterator if weighted else RandomWalkIterator
            kw = ({} if no_edge_handling is None
                  else {"no_edge_handling": no_edge_handling})
            walker = cls(graph_or_walker, walk_length, seed=self.seed, **kw)
            self.num_vertices = graph_or_walker.num_vertices()
        else:
            walker = graph_or_walker
            self.num_vertices = walker.graph.num_vertices()
        seqs = walk_sequences(walker, walks_per_vertex)
        # hierarchical softmax over vertex frequency, as the reference's
        # GraphHuffman; every vertex is kept regardless of frequency
        self.vectors = SequenceVectors(
            layer_size=self.vector_size, window_size=self.window_size,
            min_word_frequency=1, epochs=epochs,
            learning_rate=self.learning_rate, negative=0, use_hs=True,
            seed=self.seed, use_engine=self.use_engine,
            engine_ep=self.engine_ep, engine_dp=self.engine_dp)
        self.vectors.fit(seqs)
        return self

    # ------------------------------------------------------------- queries
    def get_vertex_vector(self, idx: int) -> np.ndarray:
        vec = self.vectors.get_word_vector(str(idx))
        if vec is None:
            raise KeyError(f"vertex {idx} not in model")
        return vec

    def similarity(self, a: int, b: int) -> float:
        return self.vectors.similarity(str(a), str(b))

    def vertices_nearest(self, idx: int, top_n: int = 10) -> List[int]:
        return [int(w) for w in self.vectors.words_nearest(str(idx), top_n)]


class GraphVectorSerializer:
    """Text format: one line per vertex `idx\tv0\tv1...`
    (models/deepwalk/GraphVectorSerializer.writeGraphVectors)."""

    @staticmethod
    def write_graph_vectors(model: DeepWalk, path: str) -> None:
        with open(path, "w") as f:
            for i in range(model.num_vertices):
                vec = model.vectors.get_word_vector(str(i))
                if vec is None:
                    continue
                f.write(str(i) + "\t" + "\t".join(f"{v:.8g}" for v in vec)
                        + "\n")

    @staticmethod
    def load_txt_vectors(path: str) -> dict:
        out = {}
        with open(path) as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                out[int(parts[0])] = np.array([float(v) for v in parts[1:]],
                                              dtype=np.float32)
        return out
