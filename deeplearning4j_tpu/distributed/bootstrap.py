"""Multi-process rendezvous bootstrap — the hardened replacement for the
`initialize_multihost` thin wrapper (`parallel/cluster.py`).

The reference's flagship capability is cluster training: SparkDl4jMultiLayer
scale-out on a Spark master, Akka actors for the worker bootstrap
(SURVEY §2.4; cf. SparkNet, arXiv:1511.06051). The TPU-native data plane is
`jax.distributed` + XLA collectives over ICI/DCN — but MPI-style
multi-process training (arXiv:1810.11112) shows the bootstrap/rendezvous
layer is its own subsystem, not a one-liner: processes race the
coordinator's bind, connects fail transiently, and a silent mis-wiring
(wrong process count, wrong device visibility) surfaces only as a hang
inside the first collective. This module owns that layer:

- **env-var contract** (`ENV_*` below): process id / process count /
  coordinator address / virtual-device count, written by
  `distributed/launcher.py` for local fleets and by
  `provision/tpu_vm.py`'s pod launch script for real TPU hosts. The
  constants are the single spelling — graftlint G009 flags literal
  copies anywhere else in the package.
- **initialize()**: `jax.distributed.initialize` with explicit retry /
  timeout / backoff on connect, automatic gloo CPU-collectives selection
  for off-TPU fleets (the installed CPU backend refuses multi-process
  programs without it), and telemetry `meta`/`span` events per process so
  a wedged rendezvous leaves evidence in each process's JSONL.

jax is imported lazily: this module must stay importable under
graftlint's no-jax package stubs (telemetry/recorder.py reads the env
contract through it).
"""

from __future__ import annotations

import os
import random
import time
from typing import Callable, Optional

# ------------------------------------------------------------ env contract
# One spelling for the rendezvous environment, shared by the local
# launcher, the TPU pod launch script, and the telemetry per-process
# log suffix. graftlint G009 keeps every other module importing these.
ENV_COORDINATOR = "DL4J_TPU_COORDINATOR"
ENV_PROCESS_ID = "DL4J_TPU_PROCESS_ID"
ENV_NUM_PROCESSES = "DL4J_TPU_NUM_PROCESSES"
ENV_LOCAL_DEVICE_COUNT = "DL4J_TPU_LOCAL_DEVICE_COUNT"
# fault-injection schedule (distributed/faults.py) — part of the same
# contract so the launcher's env block and the workers' runtime agree on
# one spelling (and G009 flags literal copies like the vars above)
ENV_FAULTS = "DL4J_TPU_FAULTS"

RENDEZVOUS_ENV_VARS = (ENV_COORDINATOR, ENV_PROCESS_ID, ENV_NUM_PROCESSES,
                       ENV_LOCAL_DEVICE_COUNT)


# ----------------------------------------------------------------- backoff

class Backoff:
    """Full-jitter exponential backoff under a max-elapsed-time cap.

    ``next_delay()`` returns how long to sleep before the next retry —
    drawn uniformly from [0, min(cap, base*2^attempt)] (the AWS
    "full jitter" scheme: a rejoin storm of N workers decorrelates
    instead of thundering-herding the coordinator in lockstep waves) —
    or ``None`` once the total elapsed time since the first call would
    exceed ``max_elapsed`` (the caller's signal to give up and raise).
    The last delay is clipped so sleeping it never overshoots the cap.

    ``pause()`` is the convenience loop body: sleep the next delay and
    return True, or return False when the budget is exhausted.

    clock/sleep/rng are injectable so unit tests assert the bounded
    total wait with a fake clock and zero real sleeping; the default rng
    seeds from the pid, giving each fleet member its own jitter stream
    while staying reproducible within a process.
    """

    def __init__(self, base: float = 0.25, cap: float = 5.0,
                 max_elapsed: float = 60.0,
                 rng: Optional[random.Random] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.base = base
        self.cap = cap
        self.max_elapsed = max_elapsed
        self._rng = rng if rng is not None else random.Random(os.getpid())
        self._clock = clock
        self._sleep = sleep
        self._attempt = 0
        self._start: Optional[float] = None

    @property
    def attempts(self) -> int:
        return self._attempt

    def next_delay(self) -> Optional[float]:
        now = self._clock()
        if self._start is None:
            self._start = now
        remaining = (self._start + self.max_elapsed) - now
        if remaining <= 0:
            return None
        upper = min(self.cap, self.base * (2.0 ** self._attempt))
        self._attempt += 1
        return min(self._rng.uniform(0.0, upper), remaining)

    def pause(self) -> bool:
        """Sleep the next jittered delay; False when max_elapsed is spent."""
        delay = self.next_delay()
        if delay is None:
            return False
        self._sleep(delay)
        return True


def rendezvous_env(coordinator_address: str, process_id: int,
                   num_processes: int,
                   local_device_count: Optional[int] = None) -> dict:
    """The env-var block one process of a fleet needs (a plain dict —
    merge it into a child's environment or print it as a launch line)."""
    env = {
        ENV_COORDINATOR: str(coordinator_address),
        ENV_PROCESS_ID: str(int(process_id)),
        ENV_NUM_PROCESSES: str(int(num_processes)),
    }
    if local_device_count:
        env[ENV_LOCAL_DEVICE_COUNT] = str(int(local_device_count))
    return env


def env_contract_present(environ=None) -> bool:
    """True when the spawning layer wired this process for rendezvous."""
    e = os.environ if environ is None else environ
    return (ENV_COORDINATOR in e and ENV_PROCESS_ID in e
            and ENV_NUM_PROCESSES in e)


def contract_from_env(environ=None) -> dict:
    """Parse the rendezvous contract: {coordinator_address, process_id,
    num_processes, local_device_count} with absent fields as None."""
    e = os.environ if environ is None else environ

    def _int(var):
        return int(e[var]) if var in e else None

    return {
        "coordinator_address": e.get(ENV_COORDINATOR),
        "process_id": _int(ENV_PROCESS_ID),
        "num_processes": _int(ENV_NUM_PROCESSES),
        "local_device_count": _int(ENV_LOCAL_DEVICE_COUNT),
    }


# --------------------------------------------------------------- lifecycle

def is_initialized() -> bool:
    """Whether jax's distributed runtime is already up in this process.
    Reads jax-internal state behind a guard (the public API has no
    query); False when jax or the internals are unavailable."""
    try:
        from jax._src import distributed as _dist  # jax internals: no API

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def shutdown() -> None:
    """Tear down the distributed runtime (no-op when never initialized)."""
    if not is_initialized():
        return
    import jax

    jax.distributed.shutdown()


def _want_cpu_collectives(environ) -> bool:
    """Off-TPU fleets need a CPU cross-process collectives backend: the
    plain CPU client refuses multi-process programs ("Multiprocess
    computations aren't implemented on the CPU backend"). Decide from the
    environment BEFORE backends initialize (querying jax would initialize
    them, which must not happen before jax.distributed.initialize)."""
    if ENV_LOCAL_DEVICE_COUNT in environ:
        return True
    return "cpu" in environ.get("JAX_PLATFORMS", "").lower()


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None, *,
               local_device_ids=None,
               cpu_collectives: Optional[str] = "auto",
               connect_timeout: float = 90.0,
               max_backoff: float = 5.0,
               init_timeout: Optional[float] = None) -> dict:
    """Bring up jax's multi-process runtime with rendezvous hardening.

    Arguments default from the env contract (``rendezvous_env``); on a
    Cloud TPU pod slice everything may stay None and jax auto-detects the
    topology from the metadata server. Returns an info dict
    {process_id, num_processes, local_devices, global_devices,
    coordinator, attempts} and emits one telemetry ``meta`` event plus a
    ``distributed_init`` span per process. Idempotent: a second call
    returns immediately.

    connect_timeout / max_backoff: outer retry loop around connect-time
    failures (coordinator not yet bound, transient refusals) — failed
    attempts back off with FULL-JITTER exponential delays capped at
    max_backoff seconds each, under a connect_timeout max-elapsed cap
    (see `Backoff`: a rejoin storm after an elastic re-form must not
    thundering-herd the coordinator). init_timeout: forwarded to jax's
    own initialization_timeout (how long jax itself waits inside ONE
    attempt). cpu_collectives: "auto" picks gloo for CPU fleets,
    None/"" disables, or name a backend explicitly.
    """
    from deeplearning4j_tpu.distributed.faults import active_faults
    from deeplearning4j_tpu.telemetry.recorder import get_default

    # injected `delay-connect` fault: sleep BEFORE touching the
    # coordinator, simulating a late worker racing the rendezvous
    active_faults().delay_connect()

    environ = os.environ
    contract = contract_from_env(environ)
    if coordinator_address is None:
        coordinator_address = contract["coordinator_address"]
    if num_processes is None:
        num_processes = contract["num_processes"]
    if process_id is None:
        process_id = contract["process_id"]

    rec = get_default()
    if is_initialized():
        import jax

        info = {"process_id": jax.process_index(),
                "num_processes": jax.process_count(),
                "local_devices": jax.local_device_count(),
                "global_devices": jax.device_count(),
                "coordinator": coordinator_address, "attempts": 0}
        rec.event("span", name="distributed_init", ok=True, seconds=0.0,
                  already_initialized=True, **{k: info[k] for k in
                                               ("process_id",
                                                "num_processes")})
        return info

    # virtual-device forcing must precede backend initialization; the
    # flags are pure env mutations here (asserting device counts would
    # initialize backends too early)
    if contract["local_device_count"]:
        from deeplearning4j_tpu.util.virtual_devices import cpu_device_flags

        environ["XLA_FLAGS"] = cpu_device_flags(
            contract["local_device_count"], environ.get("XLA_FLAGS", ""))
        environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax

    if cpu_collectives == "auto":
        cpu_collectives = "gloo" if _want_cpu_collectives(environ) else None
    if cpu_collectives:
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              cpu_collectives)
        except Exception:
            # newer jax generations select CPU collectives automatically
            # (or renamed the flag); proceed and let the first collective
            # surface a real incompatibility
            pass

    kwargs = {"coordinator_address": coordinator_address,
              "num_processes": num_processes, "process_id": process_id,
              "local_device_ids": local_device_ids}
    kwargs = {k: v for k, v in kwargs.items() if v is not None}
    if init_timeout is not None:
        kwargs["initialization_timeout"] = init_timeout

    backoff = Backoff(base=0.25, cap=max_backoff,
                      max_elapsed=connect_timeout)
    with rec.span("distributed_init", process_id=process_id,
                  num_processes=num_processes,
                  coordinator=coordinator_address) as span:
        while True:
            try:
                jax.distributed.initialize(**kwargs)
                break
            except Exception as exc:
                try:  # clear any half-initialized client before retrying
                    jax.distributed.shutdown()
                except Exception:
                    pass
                if not backoff.pause():
                    rec.error("distributed_init", exc=exc,
                              attempt=backoff.attempts + 1,
                              process_id=process_id,
                              coordinator=coordinator_address)
                    raise
        info = {"process_id": jax.process_index(),
                "num_processes": jax.process_count(),
                "local_devices": jax.local_device_count(),
                "global_devices": jax.device_count(),
                "coordinator": coordinator_address,
                "attempts": backoff.attempts + 1}
        span["attempts"] = backoff.attempts + 1
    rec.meta(distributed=info)
    return info
