"""Process-spanning meshes and host-data globalization.

A multi-process fleet (``distributed/bootstrap.py``) sees one global
device set: ``jax.devices()`` spans every process, and a ``Mesh`` built
over it turns the existing ``net.set_mesh`` data-parallel path into a
cross-process pjit program — XLA's allreduce over ICI/DCN (gloo on CPU
fleets) replaces the coordinator's host-side parameter averaging
entirely (SURVEY §2.4; the SparkDl4jMultiLayer aggregate-and-broadcast
becomes one compiled collective).

The one host-side wrinkle: a process can only hand jax data for its OWN
devices. Parameters ride through jit's input handling (every process
holds identical values, so the replicated placement is well-defined),
but each process's *batch* is its local shard of the global batch —
``globalize_batch`` assembles those shards into global arrays
(``jax.make_array_from_process_local_data``), and the containers'
``_batch_dict`` routes through it whenever the active mesh spans
processes. ``local_shard`` is the complementary host-side splitter for
code that starts from a full dataset on every process.

jax imports stay inside functions: the module (and the ``distributed``
package) must remain importable under graftlint's no-jax stubs.
"""

from __future__ import annotations

import numpy as np


def make_global_mesh(axes=None):
    """A Mesh over the GLOBAL device set (every process's devices, in
    jax's process-major enumeration — consecutive device blocks belong
    to consecutive processes). Same axes spec as `parallel.mesh.make_mesh`
    ({axis: size}, -1 = all remaining); defaults to pure DP."""
    import jax

    from deeplearning4j_tpu.parallel.mesh import make_mesh

    return make_mesh(axes or {"data": -1}, devices=jax.devices())


def spans_processes(mesh) -> bool:
    """True when the mesh's devices live in more than one OS process."""
    from deeplearning4j_tpu.parallel.mesh import spans_processes as _sp

    return _sp(mesh)


def globalize_batch(batch, mesh, data_axis: str = "data"):
    """Assemble per-process local batch shards into global arrays.

    Every leaf of ``batch`` is this process's slice of the global batch
    (leading dim = local batch); the returned leaves are global
    ``jax.Array``s sharded over ``data_axis`` (global leading dim = sum
    of the processes' local dims). ``data_axis=None`` (or an axis the
    mesh lacks) replicates instead — every process must then hold the
    full identical value. Leaves that are already process-spanning
    global arrays pass through untouched; on a single-process mesh the
    batch is returned as-is (the jit path's sharding handles it).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if not spans_processes(mesh):
        return batch
    shard_spec = (P(data_axis) if data_axis and data_axis in mesh.axis_names
                  else P())

    def leaf(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return x  # already a global array
        arr = np.asarray(x)
        spec = shard_spec if arr.ndim else P()
        sharding = NamedSharding(mesh, spec)
        if spec == P():
            # replicated: every process holds the full value; callback
            # placement avoids cross-process transfers
            return jax.make_array_from_callback(arr.shape, sharding,
                                                lambda idx: arr[idx])
        return jax.make_array_from_process_local_data(sharding, arr)

    return jax.tree.map(leaf, batch)


def globalize_full(x, mesh, data_axis: str = "data"):
    """Global array from a FULL host value held identically on every
    process (the inference path: `output()`/`evaluate()` take the whole
    batch, unlike `fit()`'s per-process shards). Sharded over
    ``data_axis`` when the mesh has it — each process materializes only
    its addressable slices via callback — else replicated."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    arr = np.asarray(x)
    spec = (P(data_axis) if data_axis and data_axis in mesh.axis_names
            and arr.ndim else P())
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def local_shard(x, axis: int = 0):
    """This process's contiguous slice of a full host array: the
    process-major split matching ``make_global_mesh``'s device order
    (process i gets rows [i*B/N, (i+1)*B/N)) — the ONE split rule,
    shared with the input pipeline's shard assignment
    (`data/sharding.process_slice`), so iterator sharding and host-array
    sharding can never disagree about which rows a process owns."""
    import jax

    from deeplearning4j_tpu.data.sharding import local_rows

    return local_rows(x, jax.process_index(), jax.process_count(),
                      axis=axis)
