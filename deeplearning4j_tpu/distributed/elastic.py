"""Elastic fault-tolerant training: checkpoint-resume recovery for the
multi-process fleet.

PR 4's runtime proved the 2-process pjit mesh; this module makes the
fleet *survivable*. The failure model (ARCHITECTURE §Distributed runtime
failure matrix): one worker dies mid-fit — SIGKILLed by a preemption or
the fault harness (`distributed/faults.py`), SIGABRT'd by the jax 0.4.x
"Deadline Exceeded" death, or wedged until the launcher reaps it — and
on this jax generation the survivors cannot simply continue: the gloo
world is broken and every further collective fails. Recovery is
therefore *generational*, the SparkNet coarse-sync shape
(arXiv:1511.06051) rather than in-place peer patching, and all of it
stays off the hot collective path (arXiv:1810.11112):

1. **While healthy**, every process materializes the post-step host
   values in lockstep and process 0 persists them through
   `util/orbax_checkpoint.ShardedCheckpointer.save(host=True)` — a
   process-count-portable checkpoint (restores onto N' processes, or 1).
2. **On a peer's death**, a surviving worker that sees the failure as a
   Python exception checkpoints the last COMPLETED step (its params are
   untouched by the failed step) and exits `RESUMABLE_EXIT_CODE`;
   workers that die the hard SIGABRT way are covered by the cadence
   checkpoint. Either way the step's evidence is already in telemetry.
3. **The supervisor** (`ElasticSupervisor`, launcher-side) classifies
   every exit, tears down the dead rendezvous (stragglers are reaped by
   the launch deadline; each generation gets a fresh coordinator port),
   journals the re-form durably through the `ClusterCoordinator`
   config registry, and relaunches at N' = max(survivors,
   min_processes) — topping up with *replacement* workers when the
   floor requires it (control-plane rank adoption:
   `ClusterClient(replace_dead=True)`). The re-form re-*plans* the
   placement for the new fleet shape instead of reusing the old roles
   (`_replan` -> `reshard/search.py`, journaled as
   `elastic/placement/<gen>` and named in the `reform` fault event).
4. **Rejoining workers** build the searched placement's global mesh
   (`searched_global_mesh` — every process derives the identical
   winner rank-independently and emits a `placement_search` event) and
   restore the latest checkpoint through the
   portable resharding engine (`net.resume_from(ckpt,
   target_mesh=mesh)` — `reshard/` plans the recorded checkpoint
   placement onto this generation's N'-process mesh and each process
   reads only the slices its devices need; no full-tree host gathers),
   so the continuous step counter and `batch_for_step`
   (`nn/training.fit_steps`) make the resumed run optimize the
   identical batch sequence an uninterrupted run would have seen.

jax is imported lazily: the module must stay importable under
graftlint's no-jax package stubs.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from deeplearning4j_tpu.distributed import faults as faults_mod
from deeplearning4j_tpu.distributed.faults import RESUMABLE_EXIT_CODE

# exit classes that mean "this worker is gone" (vs rejoining next gen)
_DEAD_CLASSES = frozenset({
    faults_mod.EXIT_SIGABRT, faults_mod.EXIT_DEADLINE,
    faults_mod.EXIT_INJECTED_KILL, faults_mod.EXIT_ERROR,
})

GEN_KEY = "elastic/gen"  # coordinator config key: last attempted generation
# supervisor -> worker: the step budget every generation trains toward
ENV_TOTAL_STEPS = "DL4J_TPU_ELASTIC_TOTAL_STEPS"


# ------------------------------------------------------------ worker side

def searched_global_mesh(net=None, *, objective=None):
    """The elastic re-*plan* (ROADMAP "automatic placement search"):
    instead of inheriting the dead generation's hand-specified roles, a
    (re-)formed generation searches the best placement for its OWN
    fleet shape — `reshard/search.search_placement` over
    (process_count, local device count) — and builds the global mesh
    the winner names. The search is rank- and clock-independent, so
    every member computes the identical winner without coordination
    (the same discipline as `plan_reshard`), and each emits the typed
    `placement_search` telemetry event before any mesh exists — the
    per-generation record tests/test_elastic.py reads back.

    Returns ``(mesh, axes, result)``: the process-spanning Mesh, the
    role->axis dict for ``net.set_mesh(mesh, axes=axes)``, and the full
    ranked `SearchResult` (``result.winner`` is the Placement).
    """
    import time

    import jax

    from deeplearning4j_tpu.distributed.global_mesh import make_global_mesh
    from deeplearning4j_tpu.reshard import search as search_mod

    fleet = search_mod.FleetShape(jax.process_count(),
                                  len(jax.local_devices()))
    profile = (search_mod.profile_net(net) if net is not None
               else search_mod.GENERIC_PROFILE)
    t0 = time.perf_counter()
    result = _search_with_batch_fallback(profile, fleet, objective)
    search_mod.emit_search_event(
        result, path="elastic",
        search_ms=(time.perf_counter() - t0) * 1e3,
        process_id=jax.process_index(),
        num_processes=jax.process_count())
    winner = result.winner
    mesh = make_global_mesh(dict(winner.mesh_axes))
    axes = {role: ax for role, ax in winner.roles}
    return mesh, axes, result


def _search_with_batch_fallback(profile, fleet, objective):
    """A re-plan must never kill the fleet over a MODELING mismatch:
    when every candidate dies on batch divisibility (the objective's
    proxy batch, not the worker's real one), re-model with the nearest
    batch that tiles the fleet and search again. Genuine infeasibility
    (e.g. nothing fits the HBM budget) still raises."""
    import dataclasses

    from deeplearning4j_tpu.reshard import search as search_mod

    objective = objective or search_mod.Objective()
    try:
        return search_mod.search_placement(profile, fleet,
                                           objective=objective)
    except search_mod.SearchError:
        b = objective.global_batch
        rounded = -(-b // fleet.n_devices) * fleet.n_devices
        if rounded == b:
            raise
        return search_mod.search_placement(
            profile, fleet,
            objective=dataclasses.replace(objective,
                                          global_batch=rounded))

def run_elastic_steps(net, batch_for_step, total_steps: int, *,
                      checkpoint_dir: str, checkpoint_every: int = 1):
    """The worker-side elastic fit loop (call after `bootstrap.initialize`,
    `net.resume_from(checkpoint_dir, target_mesh=mesh)` — the resharded
    restore — and `set_mesh` on the global mesh).

    Runs `nn/training.fit_steps` from the net's restored step to
    ``total_steps``; after each completed step the post-step host values
    are checkpointed every ``checkpoint_every`` steps (plus always at the
    final step), and any kill/hang fault scheduled for that step fires.
    A peer's death surfacing as a Python exception triggers the rescue
    path: checkpoint the last completed step, emit the telemetry trail,
    and exit ``RESUMABLE_EXIT_CODE`` so the supervisor counts this
    worker as a survivor for the next generation.
    """
    import jax

    from deeplearning4j_tpu.nn.training import fit_steps
    from deeplearning4j_tpu.telemetry.recorder import get_default
    from deeplearning4j_tpu.util.orbax_checkpoint import ShardedCheckpointer

    rec = get_default()
    ckptr = ShardedCheckpointer(checkpoint_dir)
    faults = faults_mod.active_faults()
    start = net.iteration_count

    def on_step(step):
        if step % checkpoint_every == 0 or step == total_steps:
            ckptr.save(net, step, host=True)
        faults.check_step(step)

    # a typed "I am resuming from `start`" mark in this process's JSONL
    rec.event("span", name="elastic_resume", ok=True, seconds=0.0,
              start_step=start, total_steps=total_steps,
              process_id=jax.process_index(),
              num_processes=jax.process_count())
    try:
        fit_steps(net, batch_for_step, total_steps, on_step=on_step)
    except Exception as exc:
        # a dead peer usually lands here as an XlaRuntimeError from the
        # failed collective; params still hold the last COMPLETED step
        rec.error("elastic_step", exc=exc, step=net.iteration_count)
        try:
            ckptr.save(net, net.iteration_count, host=True)
            saved = True
        except Exception as save_exc:  # broken world: cadence ckpt covers
            rec.error("elastic_rescue_save", exc=save_exc,
                      step=net.iteration_count)
            saved = False
        rec.fault("peer-loss-exit", step=net.iteration_count,
                  rescue_checkpoint=saved, resumable=True)
        raise SystemExit(RESUMABLE_EXIT_CODE)
    return net


# -------------------------------------------------------- supervisor side

@dataclass
class FleetGeneration:
    """One launch attempt: its size, per-process results, and the death
    accounting that sized the next generation."""

    gen: int
    n_processes: int
    results: list
    exit_classes: List[str] = field(default_factory=list)

    @property
    def dead(self) -> List[int]:
        return [r.process_id for r in self.results
                if r.exit_class in _DEAD_CLASSES]

    @property
    def clean(self) -> bool:
        return all(r.exit_class == faults_mod.EXIT_CLEAN
                   for r in self.results)


@dataclass
class ElasticRunResult:
    generations: List[FleetGeneration]
    total_steps: int

    @property
    def final_n(self) -> int:
        return self.generations[-1].n_processes


class ElasticError(RuntimeError):
    """The fleet could not finish within max_reforms generations."""


class ElasticSupervisor:
    """Launcher-side recovery supervisor: run a worker fleet to
    completion across worker deaths.

    ``argv`` is the worker program (it must follow the worker-side
    contract above: resume from ``checkpoint_dir``, run
    `run_elastic_steps`, exit 0 when ``total_steps`` is reached). Each
    generation launches through `launcher.launch_local` — fresh
    coordinator port, wall-clock deadline as the hard straggler bound —
    and the supervisor journals every generation into a durable
    `ClusterCoordinator` (``snapshot_path``): a restarted supervisor
    resumes the generation count, and replacement workers adopting dead
    ranks go through the same coordinator's ``replace_dead``
    registration. ``faults`` (a `FaultSchedule`) applies to generation 0
    only — the injected failure, not an afterlife curse.
    """

    def __init__(self, argv: Sequence[str], *, n_processes: int,
                 checkpoint_dir: str, total_steps: int,
                 min_processes: int = 1, max_reforms: int = 3,
                 local_device_count: Optional[int] = 2,
                 gen_timeout: float = 240.0, grace: float = 5.0,
                 death_grace: float = 20.0,
                 faults=None, snapshot_path: Optional[str] = None,
                 extra_env: Optional[dict] = None,
                 echo: Optional[Callable[[str], None]] = None,
                 cwd: Optional[str] = None):
        if min_processes < 1:
            raise ValueError("min_processes must be >= 1")
        if min_processes > n_processes:
            raise ValueError("min_processes cannot exceed n_processes")
        self.argv = list(argv)
        self.n_processes = n_processes
        self.checkpoint_dir = checkpoint_dir
        self.total_steps = total_steps
        self.min_processes = min_processes
        self.max_reforms = max_reforms
        self.local_device_count = local_device_count
        self.gen_timeout = gen_timeout
        self.grace = grace
        # dead-rendezvous teardown: after the first death, survivors get
        # this long to rescue-checkpoint and exit resumable on their own
        # before the launcher reaps them (on jax 0.4.x they usually
        # cannot — the coordination service aborts them from a blocked
        # collective — so waiting longer buys nothing; the cadence
        # checkpoint is the durable record either way)
        self.death_grace = death_grace
        self.faults = (faults_mod.FaultSchedule.parse(faults)
                       if faults is not None
                       and not isinstance(faults, faults_mod.FaultSchedule)
                       else faults)
        self.extra_env = dict(extra_env or {})
        self.echo = echo
        self.cwd = cwd
        from deeplearning4j_tpu.parallel.cluster import ClusterCoordinator

        # the durable control plane: generation journal + rank registry
        # (replacement workers adopt dead ranks through it); with
        # snapshot_path every re-form survives a supervisor restart too
        self.coordinator = ClusterCoordinator(
            snapshot_path=snapshot_path).start()

    def close(self) -> None:
        self.coordinator.shutdown()

    # ------------------------------------------------------------- run
    def run(self) -> ElasticRunResult:
        from deeplearning4j_tpu.distributed.launcher import launch_local
        from deeplearning4j_tpu.telemetry.recorder import (ENV_VAR,
                                                           get_default)
        from deeplearning4j_tpu.telemetry.trace import (MemoryWatch,
                                                        StragglerWatch)

        rec = get_default()
        generations: List[FleetGeneration] = []
        gen = int(self.coordinator.read_config(GEN_KEY, -1)) + 1
        n = self.n_processes
        env = dict(self.extra_env)
        env.setdefault(ENV_TOTAL_STEPS, str(self.total_steps))
        # the heartbeat-path straggler consumer: while a generation
        # runs, tail its per-process telemetry shards and put typed
        # `anomaly` events on the record the moment the fleet's step
        # completions skew (or a member stalls) — the supervisor sees a
        # sick generation BEFORE the launch deadline reaps it
        tpath = env.get(ENV_VAR) or os.environ.get(ENV_VAR)
        watch = (StragglerWatch(tpath, recorder=rec)
                 if tpath else None)
        # the memory-path consumer, same shape: leaks / headroom
        # breaches / cost drift surface as typed anomalies while the
        # generation runs, so the supervisor's journal records a
        # memory-sick fleet alongside a slow one
        memwatch = (MemoryWatch(tpath, recorder=rec)
                    if tpath else None)

        def on_poll():
            if watch is not None:
                watch.poll()
            if memwatch is not None:
                memwatch.poll()

        while True:
            self.coordinator.record_config(GEN_KEY, gen)
            with rec.span("elastic_generation", gen=gen,
                          n_processes=n) as span:
                results = launch_local(
                    self.argv, n,
                    local_device_count=self.local_device_count,
                    timeout=self.gen_timeout, grace=self.grace,
                    death_grace=self.death_grace,
                    faults=self.faults if gen == 0 else None,
                    extra_env=env, echo=self.echo, cwd=self.cwd,
                    on_poll=on_poll if tpath else None)
                if watch is not None:
                    # one forced pass over the generation's full record
                    # so a skew that landed between polls still makes
                    # the journal before the re-form decision
                    watch.poll(force=True)
                    span["straggler_anomalies"] = len(watch.findings)
                if memwatch is not None:
                    memwatch.poll(force=True)
                    span["memory_anomalies"] = len(memwatch.findings)
                g = FleetGeneration(
                    gen=gen, n_processes=n, results=results,
                    exit_classes=[r.exit_class for r in results])
                generations.append(g)
                span["exit_classes"] = g.exit_classes
                self.coordinator.record_config(
                    f"elastic/members/{gen}",
                    {"n_processes": n, "exit_classes": g.exit_classes})
            if g.clean:
                return ElasticRunResult(generations=generations,
                                        total_steps=self.total_steps)
            survivors = n - len(g.dead)
            n_next = max(survivors, self.min_processes)
            replacements = n_next - survivors
            if len(generations) > self.max_reforms:
                raise ElasticError(
                    f"fleet did not finish within {self.max_reforms} "
                    f"re-forms; exit classes per generation: "
                    f"{[h.exit_classes for h in generations]}")
            replan = self._replan(n_next, gen=gen + 1)
            self.coordinator.record_config(
                f"elastic/placement/{gen + 1}", replan.winner.to_json())
            rec.fault("reform", gen=gen + 1, n_processes=n_next,
                      survivors=survivors, replacements=replacements,
                      dead=g.dead, prior_exit_classes=g.exit_classes,
                      placement=replan.winner.describe(),
                      straggler_anomalies=(len(watch.findings)
                                           if watch is not None else 0))
            gen += 1
            n = n_next

    def _replan(self, n_processes: int, *, gen: int):
        """The supervisor half of the elastic re-plan: rank the next
        generation's fleet shape BEFORE relaunching — the re-formed
        workers re-derive the identical winner rank-independently
        through `searched_global_mesh` — and put the search on the
        record (`placement_search` event, path="reform") plus the
        durable coordinator journal. With no model in-process the
        generic profile ranks data-axis coverage + the zero1 choice,
        which is exact under the spanning data-role-only constraint."""
        import time

        from deeplearning4j_tpu.reshard import search as search_mod

        fleet = search_mod.FleetShape(n_processes,
                                      self.local_device_count or 1)
        t0 = time.perf_counter()
        result = _search_with_batch_fallback(search_mod.GENERIC_PROFILE,
                                             fleet, None)
        search_mod.emit_search_event(
            result, path="reform", gen=gen,
            search_ms=(time.perf_counter() - t0) * 1e3)
        return result


def worker_total_steps(default: Optional[int] = None) -> int:
    """The supervisor-announced step budget, from the env it gives every
    generation (worker-side convenience for `run_elastic_steps` callers).
    """
    val = os.environ.get(ENV_TOTAL_STEPS)
    if val is None:
        if default is None:
            raise KeyError(f"{ENV_TOTAL_STEPS} is not set — launch "
                           "through ElasticSupervisor or pass "
                           "total_steps explicitly")
        return default
    return int(val)


def main_argv(worker_script: str, *args: str) -> List[str]:
    """`argv` for a python worker script run by the current interpreter."""
    return [sys.executable, worker_script, *list(args)]
