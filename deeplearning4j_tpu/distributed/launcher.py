"""Local multi-process fleet launcher — the piece that makes the
multi-process runtime *testable off-TPU*.

Spawns N OS processes running the same program, each wired with the
rendezvous env contract (`distributed/bootstrap.py`) and, by default,
given 4 virtual CPU devices via
``XLA_FLAGS=--xla_force_host_platform_device_count`` — the 2-process x
4-device topology of SURVEY §4.5 without any accelerator. Per-process
stdout/stderr is streamed line-by-line with a ``[pN]`` prefix and kept
for post-mortems; a wall-clock deadline terminates and then kills
stragglers so a wedged rendezvous can never hang a test run.

``launch_plan`` renders the same fleet as copy-pastable shell lines —
the CLI's ``--multiprocess`` dry-run output and the README quickstart.
"""

from __future__ import annotations

import os
import re
import shlex
import socket
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from deeplearning4j_tpu.distributed import bootstrap


@dataclass
class ProcessResult:
    """Outcome of one fleet member: exit code (None while running or when
    the reaper had to SIGKILL a straggler that never reported one),
    captured log lines, and whether the launch deadline expired on it."""

    process_id: int
    returncode: Optional[int] = None
    lines: List[str] = field(default_factory=list)
    timed_out: bool = False

    @property
    def output(self) -> str:
        return "\n".join(self.lines)


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (bind-0 probe). Racy by nature —
    good enough for same-host fleets spawned immediately after."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


def _process_env(coordinator: str, process_id: int, n_processes: int,
                 local_device_count: Optional[int],
                 extra_env: Optional[dict]) -> dict:
    env = bootstrap.rendezvous_env(coordinator, process_id, n_processes,
                                   local_device_count)
    if local_device_count:
        from deeplearning4j_tpu.util.virtual_devices import cpu_device_flags

        env["JAX_PLATFORMS"] = "cpu"
        # the fleet's topology must be EXACT: strip any inherited device
        # forcing (e.g. the test harness's own) before applying ours,
        # keeping unrelated inherited XLA flags
        flags = (extra_env or {}).get("XLA_FLAGS",
                                      os.environ.get("XLA_FLAGS", ""))
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       "", flags).strip()
        env["XLA_FLAGS"] = cpu_device_flags(local_device_count, flags)
    if extra_env:
        env.update({k: v for k, v in extra_env.items() if k != "XLA_FLAGS"})
    return env


def _pump(proc, process_id: int, lines: List[str],
          echo: Optional[Callable[[str], None]]) -> None:
    """Reader thread: stream one process's merged stdout/stderr into its
    result (and through `echo` with the ``[pN]`` prefix)."""
    for raw in iter(proc.stdout.readline, b""):
        line = raw.decode("utf-8", errors="replace").rstrip("\n")
        lines.append(line)
        if echo is not None:
            echo(f"[p{process_id}] {line}")
    proc.stdout.close()


def launch_local(argv: Sequence[str], n_processes: int = 2, *,
                 local_device_count: Optional[int] = 4,
                 timeout: float = 300.0, grace: float = 5.0,
                 coordinator_port: Optional[int] = None,
                 extra_env: Optional[dict] = None,
                 echo: Optional[Callable[[str], None]] = None,
                 cwd: Optional[str] = None) -> List[ProcessResult]:
    """Run ``argv`` as an N-process rendezvous fleet on this host.

    Every child gets the env contract (coordinator on a free local port
    unless ``coordinator_port`` pins one) plus virtual-CPU forcing when
    ``local_device_count`` is set (None: inherit the real platform).
    Blocks until every process exits or ``timeout`` seconds elapse; on
    expiry the whole fleet is terminated, then killed after ``grace``
    seconds — stragglers are always reaped. Results arrive in process-id
    order with captured logs; ``echo`` (e.g. ``print``) streams lines
    live as ``[pN] ...``.
    """
    from deeplearning4j_tpu.telemetry.recorder import get_default

    coordinator = f"127.0.0.1:{coordinator_port or free_port()}"
    argv = list(argv)
    procs: List[subprocess.Popen] = []
    threads: List[threading.Thread] = []
    results = [ProcessResult(i) for i in range(n_processes)]
    rec = get_default()
    with rec.span("distributed_launch", n_processes=n_processes,
                  argv0=argv[0], coordinator=coordinator) as span:
        base = dict(os.environ)
        for i in range(n_processes):
            env = dict(base)
            env.update(_process_env(coordinator, i, n_processes,
                                    local_device_count, extra_env))
            p = subprocess.Popen(argv, env=env, cwd=cwd,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
            t = threading.Thread(target=_pump,
                                 args=(p, i, results[i].lines, echo),
                                 daemon=True)
            t.start()
            procs.append(p)
            threads.append(t)
        deadline = time.monotonic() + timeout
        for i, p in enumerate(procs):
            try:
                results[i].returncode = p.wait(
                    timeout=max(deadline - time.monotonic(), 0.01))
            except subprocess.TimeoutExpired:
                break
        stragglers = [i for i, p in enumerate(procs) if p.poll() is None]
        if stragglers:
            for i in stragglers:
                results[i].timed_out = True
                procs[i].terminate()
            kill_at = time.monotonic() + grace
            for i in stragglers:
                try:
                    procs[i].wait(timeout=max(kill_at - time.monotonic(),
                                              0.1))
                except subprocess.TimeoutExpired:
                    procs[i].kill()
        for i, p in enumerate(procs):
            if results[i].returncode is None and not results[i].timed_out:
                results[i].returncode = p.poll()
        for t in threads:
            t.join(timeout=2.0)
        span["returncodes"] = [r.returncode for r in results]
        span["timed_out"] = [r.process_id for r in results if r.timed_out]
    return results


def launch_plan(argv: Sequence[str], n_processes: int = 2, *,
                local_device_count: Optional[int] = 4,
                coordinator: Optional[str] = None) -> List[str]:
    """The same fleet as printable shell lines (dry run): one
    env-prefixed command per process, backgrounded, plus a ``wait``.
    What ``cli --multiprocess N`` prints and the README quotes."""
    coordinator = coordinator or f"127.0.0.1:{free_port()}"
    cmd = " ".join(shlex.quote(a) for a in argv)
    lines = []
    for i in range(n_processes):
        env = _process_env(coordinator, i, n_processes, local_device_count,
                           None)
        prefix = " ".join(f"{k}={shlex.quote(v)}"
                          for k, v in sorted(env.items()))
        lines.append(f"{prefix} {cmd} &")
    lines.append("wait")
    return lines
