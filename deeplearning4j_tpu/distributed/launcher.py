"""Local multi-process fleet launcher — the piece that makes the
multi-process runtime *testable off-TPU*.

Spawns N OS processes running the same program, each wired with the
rendezvous env contract (`distributed/bootstrap.py`) and, by default,
given 4 virtual CPU devices via
``XLA_FLAGS=--xla_force_host_platform_device_count`` — the 2-process x
4-device topology of SURVEY §4.5 without any accelerator. Per-process
stdout/stderr is streamed line-by-line with a ``[pN]`` prefix and kept
for post-mortems; a wall-clock deadline terminates and then kills
stragglers so a wedged rendezvous can never hang a test run.

``launch_plan`` renders the same fleet as copy-pastable shell lines —
the CLI's ``--multiprocess`` dry-run output and the README quickstart.
"""

from __future__ import annotations

import os
import re
import shlex
import signal
import socket
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from deeplearning4j_tpu.distributed import bootstrap, faults as faults_mod


@dataclass
class ProcessResult:
    """Outcome of one fleet member: exit code (None while running or when
    the reaper had to SIGKILL a straggler that never reported one),
    captured log lines, whether the launch deadline expired on it, and
    the classified exit (`classify_exit` — a bare returncode cannot
    distinguish a SIGABRT'd rendezvous from an injected kill)."""

    process_id: int
    returncode: Optional[int] = None
    lines: List[str] = field(default_factory=list)
    timed_out: bool = False
    exit_class: str = ""

    @property
    def output(self) -> str:
        return "\n".join(self.lines)


def classify_exit(returncode: Optional[int], timed_out: bool,
                  kill_injected: bool = False) -> str:
    """One fleet member's exit, as a class the supervisor can act on:

    - ``deadline-reaped``: never exited; the launcher terminated/killed
      it at the wall-clock deadline (wedged rendezvous, injected hang).
    - ``clean``: returncode 0.
    - ``resumable``: `faults.RESUMABLE_EXIT_CODE` — the worker survived
      a peer's death, checkpointed, and wants to rejoin.
    - ``injected-kill``: died by SIGKILL *and* the fault schedule named
      this process for a kill (an unscheduled SIGKILL stays ``error`` —
      the OOM killer must not be mistaken for the harness).
    - ``sigabrt``: the documented jax 0.4.x fleet death (XLA client
      aborts on "Deadline Exceeded" — ARCHITECTURE §failure matrix).
    - ``error``: any other nonzero/signal exit.
    """
    if timed_out:
        return faults_mod.EXIT_DEADLINE
    if returncode == 0:
        return faults_mod.EXIT_CLEAN
    if returncode == faults_mod.RESUMABLE_EXIT_CODE:
        return faults_mod.EXIT_RESUMABLE
    if returncode == -signal.SIGKILL and kill_injected:
        return faults_mod.EXIT_INJECTED_KILL
    if returncode == -signal.SIGABRT:
        return faults_mod.EXIT_SIGABRT
    return faults_mod.EXIT_ERROR


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (bind-0 probe). Racy by nature —
    good enough for same-host fleets spawned immediately after."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


def _process_env(coordinator: str, process_id: int, n_processes: int,
                 local_device_count: Optional[int],
                 extra_env: Optional[dict]) -> dict:
    env = bootstrap.rendezvous_env(coordinator, process_id, n_processes,
                                   local_device_count)
    if local_device_count:
        from deeplearning4j_tpu.util.virtual_devices import cpu_device_flags

        env["JAX_PLATFORMS"] = "cpu"
        # the fleet's topology must be EXACT: strip any inherited device
        # forcing (e.g. the test harness's own) before applying ours,
        # keeping unrelated inherited XLA flags
        flags = (extra_env or {}).get("XLA_FLAGS",
                                      os.environ.get("XLA_FLAGS", ""))
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       "", flags).strip()
        env["XLA_FLAGS"] = cpu_device_flags(local_device_count, flags)
    if extra_env:
        env.update({k: v for k, v in extra_env.items() if k != "XLA_FLAGS"})
    return env


def _pump(proc, process_id: int, lines: List[str],
          echo: Optional[Callable[[str], None]]) -> None:
    """Reader thread: stream one process's merged stdout/stderr into its
    result (and through `echo` with the ``[pN]`` prefix)."""
    for raw in iter(proc.stdout.readline, b""):
        line = raw.decode("utf-8", errors="replace").rstrip("\n")
        lines.append(line)
        if echo is not None:
            echo(f"[p{process_id}] {line}")
    proc.stdout.close()


def launch_local(argv: Sequence[str], n_processes: int = 2, *,
                 local_device_count: Optional[int] = 4,
                 timeout: float = 300.0, grace: float = 5.0,
                 coordinator_port: Optional[int] = None,
                 extra_env: Optional[dict] = None,
                 echo: Optional[Callable[[str], None]] = None,
                 cwd: Optional[str] = None,
                 faults=None,
                 death_grace: Optional[float] = None,
                 on_poll: Optional[Callable[[], None]] = None
                 ) -> List[ProcessResult]:
    """Run ``argv`` as an N-process rendezvous fleet on this host.

    Every child gets the env contract (coordinator on a free local port
    unless ``coordinator_port`` pins one) plus virtual-CPU forcing when
    ``local_device_count`` is set (None: inherit the real platform).
    Blocks until every process exits or ``timeout`` seconds elapse; on
    expiry the whole fleet is terminated, then killed after ``grace``
    seconds — stragglers are always reaped. Results arrive in process-id
    order with captured logs and a classified exit (`classify_exit`),
    each also echoed as a ``[pN] -- exit: <class>`` epilogue line;
    ``echo`` (e.g. ``print``) streams lines live as ``[pN] ...``.

    ``faults``: a `faults.FaultSchedule` (or spec string/list) applied to
    the named processes via the `ENV_FAULTS` contract — every injected
    fault and every observed exit class is emitted as a typed telemetry
    ``fault`` event, so the whole run is reconstructable from JSONL.

    ``death_grace``: responsive rendezvous teardown for the elastic
    supervisor. Once any member exits with a DEATH code (neither 0 nor
    the resumable code), the rest get this many seconds to notice and
    exit on their own (the rescue path) before being reaped — on jax
    0.4.x the survivors of a killed peer otherwise sit in the broken
    collective until the coordination service aborts them ~60 s later,
    and the full wall-clock ``timeout`` is the only other bound. None
    (the default) keeps the deadline as the sole reaper.

    ``on_poll``: a callback invoked on every monitor pass while the
    fleet runs — the elastic supervisor's straggler watch
    (telemetry/trace.StragglerWatch.poll) tails the per-process
    telemetry shards here and puts `anomaly` events on the record while
    a skewing generation is still alive. Exceptions are contained: a
    broken watcher never kills the launch.
    """
    from deeplearning4j_tpu.telemetry.recorder import get_default

    if faults is not None and not isinstance(faults,
                                             faults_mod.FaultSchedule):
        faults = faults_mod.FaultSchedule.parse(faults)
    coordinator = f"127.0.0.1:{coordinator_port or free_port()}"
    argv = list(argv)
    procs: List[subprocess.Popen] = []
    threads: List[threading.Thread] = []
    results = [ProcessResult(i) for i in range(n_processes)]
    rec = get_default()
    with rec.span("distributed_launch", n_processes=n_processes,
                  argv0=argv[0], coordinator=coordinator) as span:
        if faults is not None:
            for f in faults:
                rec.fault(f.kind, process_id=f.process_id, step=f.step,
                          spec=f.spec(), injected=True)
        base = dict(os.environ)
        for i in range(n_processes):
            env = dict(base)
            env.update(_process_env(coordinator, i, n_processes,
                                    local_device_count, extra_env))
            if faults is not None and faults.for_process(i):
                env[bootstrap.ENV_FAULTS] = faults.to_env()
            p = subprocess.Popen(argv, env=env, cwd=cwd,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
            t = threading.Thread(target=_pump,
                                 args=(p, i, results[i].lines, echo),
                                 daemon=True)
            t.start()
            procs.append(p)
            threads.append(t)
        deadline = time.monotonic() + timeout
        death_at = None
        pending = set(range(n_processes))
        while pending:
            now = time.monotonic()
            if now >= deadline or (death_at is not None
                                   and now >= death_at):
                break
            for i in sorted(pending):
                rc = procs[i].poll()
                if rc is None:
                    continue
                results[i].returncode = rc
                pending.discard(i)
                if death_grace is not None and death_at is None and \
                        rc not in (0, faults_mod.RESUMABLE_EXIT_CODE):
                    death_at = time.monotonic() + death_grace
                    span["death_grace_tripped_by"] = i
            if on_poll is not None:
                try:
                    on_poll()
                except Exception:
                    pass  # the watch is advisory; the launch is not
            if pending:
                time.sleep(0.05)
        stragglers = [i for i, p in enumerate(procs) if p.poll() is None]
        if stragglers:
            for i in stragglers:
                results[i].timed_out = True
                procs[i].terminate()
            kill_at = time.monotonic() + grace
            for i in stragglers:
                try:
                    procs[i].wait(timeout=max(kill_at - time.monotonic(),
                                              0.1))
                except subprocess.TimeoutExpired:
                    procs[i].kill()
        for i, p in enumerate(procs):
            if results[i].returncode is None and not results[i].timed_out:
                results[i].returncode = p.poll()
        for t in threads:
            t.join(timeout=2.0)
        for r in results:
            injected = (faults is not None
                        and faults.kill_scheduled(r.process_id))
            r.exit_class = classify_exit(r.returncode, r.timed_out,
                                         kill_injected=injected)
            epilogue = (f"-- exit: {r.exit_class} "
                        f"(rc={r.returncode}, timed_out={r.timed_out})")
            r.lines.append(epilogue)
            if echo is not None:
                echo(f"[p{r.process_id}] {epilogue}")
            rec.fault(r.exit_class, process_id=r.process_id,
                      returncode=r.returncode, timed_out=r.timed_out,
                      observed_exit=True)
            if r.exit_class not in (faults_mod.EXIT_CLEAN,
                                    faults_mod.EXIT_RESUMABLE,
                                    faults_mod.EXIT_INJECTED_KILL,
                                    faults_mod.EXIT_DEADLINE):
                # unexpected death (SIGABRT'd rendezvous, crash): an
                # `error` event with the captured log tail for post-mortem
                rec.error("distributed_launch",
                          error=f"p{r.process_id} {r.exit_class}",
                          traceback_str="\n".join(r.lines[-40:]),
                          process_id=r.process_id,
                          returncode=r.returncode)
        span["returncodes"] = [r.returncode for r in results]
        span["exit_classes"] = [r.exit_class for r in results]
        span["timed_out"] = [r.process_id for r in results if r.timed_out]
    return results


def launch_plan(argv: Sequence[str], n_processes: int = 2, *,
                local_device_count: Optional[int] = 4,
                coordinator: Optional[str] = None) -> List[str]:
    """The same fleet as printable shell lines (dry run): one
    env-prefixed command per process, backgrounded, plus a ``wait``.
    What ``cli --multiprocess N`` prints and the README quotes."""
    coordinator = coordinator or f"127.0.0.1:{free_port()}"
    cmd = " ".join(shlex.quote(a) for a in argv)
    lines = []
    for i in range(n_processes):
        env = _process_env(coordinator, i, n_processes, local_device_count,
                           None)
        prefix = " ".join(f"{k}={shlex.quote(v)}"
                          for k, v in sorted(env.items()))
        lines.append(f"{prefix} {cmd} &")
    lines.append("wait")
    return lines
