"""Deterministic fault injection for the multi-process fleet.

A fleet that has never been killed mid-step has an untested recovery
path; this module makes worker death, hangs, slow joins, and silent
heartbeat loss *reproducible* so `distributed/elastic.py`'s recovery
supervisor (and the tier-1 tests) exercise them on demand. SparkNet
(arXiv:1511.06051) argues coarse-sync training tolerates stragglers and
restarts gracefully — but only a harness that injects those failures on
a fixed schedule can prove it, and the CUDA-aware-MPI characterization
(arXiv:1810.11112) motivates keeping all of this recovery machinery off
the hot collective path (faults fire from the host-side step loop, never
inside a traced program).

**Spec syntax** — one fault per spec, `;`-joined into a schedule:

    p1:kill@step3        SIGKILL process 1 right after its step 3 completes
    p2:hang@step4        process 2 stops making progress after step 4
    p0:delay-connect:1.5 process 0 sleeps 1.5 s before dialing the rendezvous
    p1:drop-heartbeat    process 1 silently stops heartbeating its
                         ClusterClient (coordinator reaps it; its slot
                         becomes claimable)

Serving chaos (ISSUE 13) reuses the same grammar with an `r` scope
prefix — the victim is a REPLICA (a serving worker thread inside one
engine, serving/engine.py) rather than a fleet process, and the trigger
counts that replica's own work units instead of training steps:

    r0:kill@batch3       replica 0 dies MID-BATCH while running its 3rd
                         assembled batch (a thread cannot be SIGKILLed:
                         the engine fails that batch's requests loudly
                         and lets the thread die — serving/fleet.py)
    r1:hang@batch2       replica 1 wedges mid-batch (reaped by the fleet
                         supervisor's heartbeat staleness bound)
    r0:kill@decode5      a generation replica dies mid-decode at its 5th
                         decode step (active slots fail, pages release)

Replica faults take only kill/hang with a batch/decode trigger; process
faults keep the step trigger — mixing the two is a parse error.

The schedule travels to fleet members through the env contract
(`bootstrap.ENV_FAULTS`, set by `launcher.launch_local(faults=...)`);
each process filters the schedule by its own `ENV_PROCESS_ID`, so one
string describes the whole fleet. Every fired fault emits a typed
telemetry `fault` event BEFORE acting (the recorder flushes per line, so
even a SIGKILL leaves its evidence in the JSONL).

Pure stdlib: importable under graftlint's no-jax package stubs, and
usable from processes that never import jax (the classification unit
tests run in bare interpreters).
"""

from __future__ import annotations

import os
import random
import signal
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

from deeplearning4j_tpu.distributed import bootstrap

KINDS = ("kill", "hang", "delay-connect", "drop-heartbeat")

# Exit classes the launcher reports per fleet member (see
# `launcher.classify_exit`). One spelling, shared with telemetry events
# and the elastic supervisor's death accounting.
EXIT_CLEAN = "clean"
EXIT_SIGABRT = "sigabrt"
EXIT_DEADLINE = "deadline-reaped"
EXIT_INJECTED_KILL = "injected-kill"
EXIT_RESUMABLE = "resumable"
EXIT_ERROR = "error"

# Exit code a worker uses to say "I survived a peer's death, checkpointed
# the last completed step, and want to rejoin the next generation"
# (sysexits EX_TEMPFAIL — a transient, retryable condition).
RESUMABLE_EXIT_CODE = 75


# trigger units per scope: process faults fire on training steps,
# replica faults on a serving worker's own batch / decode-step counters
SCOPES = ("process", "replica")
REPLICA_UNITS = ("batch", "decode")


@dataclass(frozen=True)
class Fault:
    """One scheduled fault: what, to whom, and when. ``process_id`` names
    the victim within its scope — a fleet process for scope "process", a
    serving replica index for scope "replica"."""

    process_id: int
    kind: str  # one of KINDS
    step: Optional[int] = None      # kill/hang trigger count
    seconds: Optional[float] = None  # delay-connect sleep
    scope: str = "process"          # one of SCOPES
    unit: str = "step"              # "step" | "batch" | "decode"

    def spec(self) -> str:
        prefix = "p" if self.scope == "process" else "r"
        s = f"{prefix}{self.process_id}:{self.kind}"
        if self.step is not None:
            s += f"@{self.unit}{self.step}"
        if self.seconds is not None:
            s += f":{self.seconds:g}"
        return s


def parse_fault(spec: str) -> Fault:
    """Parse one `pN:kind[@stepK][:seconds]` / `rN:kind@batchK|decodeK`
    spec (see module docstring)."""
    spec = spec.strip()
    head, _, rest = spec.partition(":")
    if not head[:1] in ("p", "r") or not head[1:].isdigit():
        raise ValueError(f"fault spec {spec!r}: expected 'p<N>:<kind>...' "
                         "or 'r<N>:<kind>...'")
    scope = "process" if head[0] == "p" else "replica"
    process_id = int(head[1:])
    kind, step, seconds, unit = rest, None, None, "step"
    if "@" in rest:
        kind, _, when = rest.partition("@")
        for u in ("step",) + REPLICA_UNITS:
            if when.startswith(u):
                unit, when = u, when[len(u):]
                break
        if not when.isdigit():
            raise ValueError(f"fault spec {spec!r}: bad trigger {when!r}")
        step = int(when)
    elif ":" in rest:
        kind, _, secs = rest.partition(":")
        seconds = float(secs)
    if kind not in KINDS:
        raise ValueError(f"fault spec {spec!r}: unknown kind {kind!r} "
                         f"(one of {', '.join(KINDS)})")
    if scope == "replica":
        if kind not in ("kill", "hang"):
            raise ValueError(f"fault spec {spec!r}: replica faults take "
                             "only kill/hang")
        if step is None or unit not in REPLICA_UNITS:
            raise ValueError(f"fault spec {spec!r}: replica faults need "
                             "'@batch<N>' or '@decode<N>'")
    else:
        if unit != "step":
            raise ValueError(f"fault spec {spec!r}: process faults "
                             "trigger on '@step<N>', not {unit!r}")
        if kind in ("kill", "hang") and step is None:
            raise ValueError(f"fault spec {spec!r}: {kind} needs "
                             "'@step<N>'")
        if kind == "delay-connect" and seconds is None:
            raise ValueError(f"fault spec {spec!r}: delay-connect needs "
                             "':<seconds>'")
    return Fault(process_id, kind, step=step, seconds=seconds,
                 scope=scope, unit=unit)


class FaultSchedule:
    """An ordered set of Faults for one fleet launch."""

    def __init__(self, faults: Sequence[Fault] = ()):
        self.faults: List[Fault] = list(faults)

    @classmethod
    def parse(cls, specs) -> "FaultSchedule":
        """From a `;`-joined string or an iterable of spec strings."""
        if isinstance(specs, str):
            specs = [s for s in specs.split(";") if s.strip()]
        return cls([parse_fault(s) for s in specs])

    @classmethod
    def seeded(cls, seed: int, n_processes: int, max_step: int,
               kinds: Sequence[str] = ("kill", "hang")) -> "FaultSchedule":
        """A deterministic one-fault schedule: the same seed always names
        the same victim, kind, and step (stdlib Random — reproducible
        across platforms and interpreter runs, unlike hash())."""
        rng = random.Random(seed)
        kind = kinds[rng.randrange(len(kinds))]
        victim = rng.randrange(n_processes)
        fault = Fault(victim, kind, step=rng.randint(1, max_step))
        return cls([fault])

    def to_env(self) -> str:
        return ";".join(f.spec() for f in self.faults)

    def for_process(self, process_id: int) -> List[Fault]:
        return [f for f in self.faults if f.process_id == process_id
                and f.scope == "process"]

    def for_replica(self, replica_index: int) -> List[Fault]:
        """Replica-scoped faults targeting one serving worker (the
        serving engine's chaos hooks — serving/fleet.py)."""
        return [f for f in self.faults if f.process_id == replica_index
                and f.scope == "replica"]

    def kill_scheduled(self, process_id: int) -> bool:
        return any(f.kind == "kill" for f in self.for_process(process_id))

    def __iter__(self):
        return iter(self.faults)

    def __len__(self):
        return len(self.faults)


class FaultRuntime:
    """The in-process half: the hooks a fleet member consults.

    Constructed by `active_faults()` from the env contract; a process
    outside any schedule gets an empty runtime whose hooks cost one
    attribute read. `_sleep`/`_kill` are injectable for unit tests.
    """

    def __init__(self, faults: Sequence[Fault] = (),
                 process_id: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 kill: Callable[[int, int], None] = os.kill):
        self.faults = list(faults)
        self.process_id = process_id
        self._sleep = sleep
        self._kill = kill

    def _emit(self, fault: Fault, **fields) -> None:
        from deeplearning4j_tpu.telemetry.recorder import get_default

        get_default().fault(fault.kind, process_id=self.process_id,
                            step=fault.step, spec=fault.spec(), fired=True,
                            **fields)

    @property
    def drop_heartbeat(self) -> bool:
        """True when this process must silently stop heartbeating its
        ClusterClient (consulted once per heartbeat thread)."""
        return any(f.kind == "drop-heartbeat" for f in self.faults)

    def delay_connect(self) -> float:
        """Sleep any scheduled pre-rendezvous delay (called by
        `bootstrap.initialize` before dialing); returns seconds slept."""
        total = 0.0
        for f in self.faults:
            if f.kind == "delay-connect" and f.seconds:
                self._emit(f, seconds=f.seconds)
                self._sleep(f.seconds)
                total += f.seconds
        return total

    def check_step(self, step: int) -> None:
        """Fire any kill/hang scheduled at `step` (called by the elastic
        step loop after the step completes — so the injected death
        happens between a completed collective and the next one, the
        same place a real preemption lands)."""
        for f in self.faults:
            if f.step != step:
                continue
            if f.kind == "kill":
                self._emit(f)
                self._kill(os.getpid(), signal.SIGKILL)
            elif f.kind == "hang":
                self._emit(f)
                while True:  # reaped by the launcher's wall-clock deadline
                    self._sleep(3600.0)


_EMPTY = FaultRuntime()


def active_faults(environ=None) -> FaultRuntime:
    """This process's FaultRuntime from the env contract: the schedule in
    `ENV_FAULTS` filtered by `ENV_PROCESS_ID`. Re-parses per call (cheap,
    and monkeypatched environments in tests take effect immediately);
    returns a shared empty runtime when no schedule targets us."""
    e = os.environ if environ is None else environ
    raw = e.get(bootstrap.ENV_FAULTS)
    pid_s = e.get(bootstrap.ENV_PROCESS_ID)
    if not raw or pid_s is None:
        return _EMPTY
    process_id = int(pid_s)
    mine = FaultSchedule.parse(raw).for_process(process_id)
    if not mine:
        return _EMPTY
    return FaultRuntime(mine, process_id=process_id)
