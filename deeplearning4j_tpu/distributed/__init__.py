"""Multi-process runtime: rendezvous bootstrap, local fleet launcher,
and process-spanning meshes (the reference's deeplearning4j-scaleout
bootstrap layer — Spark master / Akka worker actors — rebuilt on
jax.distributed; SURVEY §2.4).

- `bootstrap`: env-var contract + hardened `jax.distributed.initialize`
  (retry/backoff, gloo CPU collectives, per-process telemetry).
- `launcher`: N local OS processes x K virtual CPU devices with log
  streaming, wall-clock timeouts, and straggler reaping.
- `global_mesh`: the Mesh over every process's devices + per-process
  batch-shard globalization, routed through the containers' `set_mesh`.
- `faults`: deterministic fault injection (kill@step / hang@step /
  delay-connect / drop-heartbeat) through the same env contract.
- `elastic`: the recovery supervisor — checkpoint cadence, exit
  classification, generational re-form at N' processes, resume with a
  continuous step counter.

Only `bootstrap` (pure stdlib) loads eagerly; the rest resolve lazily so
importing this package never drags in jax (graftlint stub contract —
telemetry/recorder.py reads the env contract through `bootstrap`).
"""

from deeplearning4j_tpu.distributed.bootstrap import (  # noqa: F401
    ENV_COORDINATOR,
    ENV_LOCAL_DEVICE_COUNT,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
    contract_from_env,
    env_contract_present,
    initialize,
    rendezvous_env,
    shutdown,
)

_LAZY = {
    "ProcessResult": "deeplearning4j_tpu.distributed.launcher",
    "classify_exit": "deeplearning4j_tpu.distributed.launcher",
    "free_port": "deeplearning4j_tpu.distributed.launcher",
    "launch_local": "deeplearning4j_tpu.distributed.launcher",
    "launch_plan": "deeplearning4j_tpu.distributed.launcher",
    "Fault": "deeplearning4j_tpu.distributed.faults",
    "FaultSchedule": "deeplearning4j_tpu.distributed.faults",
    "active_faults": "deeplearning4j_tpu.distributed.faults",
    "ElasticSupervisor": "deeplearning4j_tpu.distributed.elastic",
    "run_elastic_steps": "deeplearning4j_tpu.distributed.elastic",
    "globalize_batch": "deeplearning4j_tpu.distributed.global_mesh",
    "globalize_full": "deeplearning4j_tpu.distributed.global_mesh",
    "local_shard": "deeplearning4j_tpu.distributed.global_mesh",
    "make_global_mesh": "deeplearning4j_tpu.distributed.global_mesh",
    "spans_processes": "deeplearning4j_tpu.distributed.global_mesh",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
