"""The resharding planner — a pure function from (source placement,
target placement, leaf layout) to a deterministic redistribution plan.

ROADMAP's portable resharding engine, layer 1 of 3 (executors live in
`reshard/executor.py`, integration in placement/elastic/serving). The
formulation follows arXiv:2112.01075 (*Memory-efficient array
redistribution through portable collective communication*): a
redistribution is a per-leaf choice among a small vocabulary of
transfer patterns, each with a computable byte cost, and the planner's
job is to pick the cheapest valid pattern and REPORT the lower bound so
the executor's achieved bytes are auditable against it. Composed with
the zero1 optimizer-state shardings (arXiv:2004.13336,
`nn/training.zero1_opt_shardings`), optimizer moments reshard through
the same plans as params.

Everything here is pure stdlib and pure data:

- no jax import (the module loads under graftlint's no-jax stubs, and
  the CLI dry-run plans a checkpoint->mesh move without touching a
  device);
- no dependence on the calling process's rank, host, or clock — the
  same placements yield the byte-identical plan on every process
  (tests/test_reshard.py re-plans under simulated process_index 0 vs 1),
  which is what lets every fleet member execute its slice of the plan
  without coordination.

Per-leaf actions:

| action           | when                                            | bytes model |
|---|---|---|
| `keep`           | identical spec, mesh layout, and process set    | 0 |
| `slice_exchange` | every dim refines (T_d a multiple of S_d)       | the lower bound: bytes a target device needs that its aligned source device does not hold |
| `allgather_shard`| coarsening or cross-dim moves                   | full leaf to every target device, minus resident |
| `host_fallback`  | only when forced (`force_host=True` — the PR 6  | gather to host + redistribute, no resident credit |
|                  | lockstep-host-checkpoint shape, kept for cost   |    |
|                  | comparison and for non-coexisting meshes)       |    |

Invariant (asserted by tier-1): for every leaf, `bytes_slice <=
bytes_gather` and `bytes_slice <= bytes_host` — the slice plan IS the
lower bound, so preferring collective plans over host gathers is
structural, not tuned. (`bytes_host` can undercut `bytes_gather` on
byte count alone — the host path sends each target device only its
shard — but it serializes through one host hop, which is why the
planner only emits it when forced.)

A malformed placement (unknown role, role on a missing axis, a spec
axis absent from the mesh, a sharded dim not divisible by its partition
count — the target-mesh-larger-than-checkpoint failure row) raises
`PlacementError` before any plan exists; the executor never sees a
half-valid plan.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from fractions import Fraction
from typing import Optional, Sequence, Tuple

# dp / tp / pp / sp / ep — the same role vocabulary as
# parallel/placement.py (ROLES); the planner re-declares it to stay
# import-free under the lint stubs.
VALID_ROLES = ("data", "model", "pipe", "expert", "seq")

KEEP = "keep"
SLICE_EXCHANGE = "slice_exchange"
ALLGATHER_SHARD = "allgather_shard"
HOST_FALLBACK = "host_fallback"
ACTIONS = (KEEP, SLICE_EXCHANGE, ALLGATHER_SHARD, HOST_FALLBACK)


class PlacementError(ValueError):
    """A placement or leaf layout the engine must refuse to plan for."""


@dataclass(frozen=True)
class Placement:
    """One side of a redistribution: mesh shape x axis roles x process
    count (+ whether zero1 shards the optimizer moments over the data
    axis). Pure data — device objects never appear here."""

    mesh_axes: Tuple[Tuple[str, int], ...]   # ordered (axis name, size)
    roles: Tuple[Tuple[str, str], ...] = ()  # (role, mesh axis) pairs
    process_count: int = 1
    zero1: bool = False

    @classmethod
    def of(cls, mesh_axes, roles=None, *, process_count: int = 1,
           zero1: bool = False) -> "Placement":
        """Build + validate from dicts ({axis: size}, {role: axis})."""
        p = cls(tuple((str(a), int(n)) for a, n in dict(mesh_axes).items()),
                tuple((str(r), str(a))
                      for r, a in dict(roles or {}).items()),
                process_count=int(process_count), zero1=bool(zero1))
        p.validate()
        return p

    # ------------------------------------------------------------ views
    @property
    def axis_sizes(self) -> dict:
        return dict(self.mesh_axes)

    @property
    def n_devices(self) -> int:
        n = 1
        for _, s in self.mesh_axes:
            n *= s
        return n

    def axis_for(self, role: str) -> Optional[str]:
        for r, a in self.roles:
            if r == role:
                return a
        return None

    # -------------------------------------------------------- validation
    def validate(self) -> "Placement":
        if not self.mesh_axes:
            raise PlacementError("placement has an empty mesh")
        seen = set()
        for ax, size in self.mesh_axes:
            if ax in seen:
                raise PlacementError(f"duplicate mesh axis {ax!r}")
            seen.add(ax)
            if size < 1:
                raise PlacementError(f"mesh axis {ax!r} has size {size}")
        for role, ax in self.roles:
            if role not in VALID_ROLES:
                raise PlacementError(
                    f"unknown role {role!r}; valid: {VALID_ROLES}")
            if ax not in seen:
                raise PlacementError(
                    f"role {role!r} maps to axis {ax!r} which is not on "
                    f"the mesh (axes: {sorted(seen)})")
        if self.process_count < 1:
            raise PlacementError(
                f"process_count must be >= 1 (got {self.process_count})")
        if self.n_devices % self.process_count:
            raise PlacementError(
                f"{self.n_devices} mesh devices do not divide over "
                f"{self.process_count} processes")
        if self.zero1:
            extra = {r for r, _ in self.roles} - {"data"}
            if extra:
                raise PlacementError(
                    "zero1 composes with the 'data' role only (got "
                    f"{sorted(extra)}) — same constraint as set_mesh")
        return self

    def to_json(self) -> dict:
        return {"mesh_axes": [list(p) for p in self.mesh_axes],
                "roles": [list(p) for p in self.roles],
                "process_count": self.process_count,
                "zero1": self.zero1}

    @classmethod
    def from_json(cls, obj: dict) -> "Placement":
        return cls.of(dict(tuple(p) for p in obj.get("mesh_axes", [])),
                      dict(tuple(p) for p in obj.get("roles", [])),
                      process_count=obj.get("process_count", 1),
                      zero1=obj.get("zero1", False))

    @classmethod
    def solo(cls) -> "Placement":
        """The trivial one-device placement (an unplaced net, a serving
        process, a checkpoint written before placements were stamped)."""
        return cls.of({"data": 1}, {"data": "data"})

    def describe(self) -> str:
        shape = "x".join(str(s) for _, s in self.mesh_axes)
        roles = ",".join(f"{r}={a}" for r, a in self.roles) or "-"
        return (f"{shape} ({roles}) p{self.process_count}"
                + ("+zero1" if self.zero1 else ""))


@dataclass(frozen=True)
class LeafLayout:
    """One pytree leaf's shape/dtype and its partition spec on each
    side. A spec is a tuple with one entry per dim: a mesh axis name or
    None (the PartitionSpec shape, as plain data)."""

    name: str
    shape: Tuple[int, ...]
    itemsize: int
    src_spec: Tuple[Optional[str], ...] = ()
    dst_spec: Tuple[Optional[str], ...] = ()

    @property
    def bytes(self) -> int:
        n = self.itemsize
        for d in self.shape:
            n *= d
        return n


@dataclass(frozen=True)
class LeafPlan:
    name: str
    action: str
    bytes_leaf: int
    bytes_moved: int
    bytes_lower_bound: int
    bytes_slice: int
    bytes_gather: int
    bytes_host: int
    src_spec: Tuple[Optional[str], ...]
    dst_spec: Tuple[Optional[str], ...]


@dataclass(frozen=True)
class ReshardPlan:
    src: Placement
    dst: Placement
    leaves: Tuple[LeafPlan, ...] = field(default_factory=tuple)

    @property
    def bytes_total(self) -> int:
        return sum(l.bytes_leaf for l in self.leaves)

    @property
    def bytes_moved(self) -> int:
        return sum(l.bytes_moved for l in self.leaves)

    @property
    def bytes_lower_bound(self) -> int:
        return sum(l.bytes_lower_bound for l in self.leaves)

    def actions(self) -> dict:
        out = {a: 0 for a in ACTIONS}
        for l in self.leaves:
            out[l.action] += 1
        return {a: n for a, n in out.items() if n}

    def summary(self) -> dict:
        """The `reshard_plan` telemetry payload (and the CLI dry-run
        totals): everything an audit needs to judge the executed move
        against the plan without re-deriving it."""
        return {"src": self.src.describe(), "dst": self.dst.describe(),
                "n_leaves": len(self.leaves), "actions": self.actions(),
                "bytes_total": self.bytes_total,
                "bytes_moved": self.bytes_moved,
                "bytes_lower_bound": self.bytes_lower_bound}


# ------------------------------------------------------------ cost model

def _partition_counts(shape, spec, placement, name):
    """Per-dim partition counts for one side, validating the spec."""
    sizes = placement.axis_sizes
    counts = []
    spec = tuple(spec) + (None,) * (len(shape) - len(spec))
    if len(spec) > len(shape):
        raise PlacementError(
            f"leaf {name!r}: spec {spec} has more entries than dims "
            f"{shape}")
    for d, ax in enumerate(spec):
        if ax is None:
            counts.append(1)
            continue
        if ax not in sizes:
            raise PlacementError(
                f"leaf {name!r}: spec names axis {ax!r} absent from the "
                f"mesh (axes: {sorted(sizes)})")
        n = sizes[ax]
        if n > 1 and shape[d] % n:
            # the target-mesh-larger-than-checkpoint failure row: a dim
            # that cannot split over the requested axis is a refused
            # plan, not a runtime surprise
            raise PlacementError(
                f"leaf {name!r}: dim {d} of {shape} does not divide over "
                f"{n}-way axis {ax!r}")
        counts.append(n)
    return counts


def _aligned_overlap(s: int, t: int) -> Fraction:
    """Resident fraction along one dim when a target block is served by
    its aligned source block (block j of t reads from block
    floor(j*s/t) of s): the summed interval overlap, exact rational."""
    if s == t:
        return Fraction(1)
    total = Fraction(0)
    for j in range(t):
        lo_t, hi_t = Fraction(j, t), Fraction(j + 1, t)
        i = (j * s) // t
        lo_s, hi_s = Fraction(i, s), Fraction(i + 1, s)
        total += max(Fraction(0), min(hi_t, hi_s) - max(lo_t, lo_s))
    return total


def plan_leaf(leaf: LeafLayout, src: Placement, dst: Placement, *,
              force_host: bool = False) -> LeafPlan:
    """Plan one leaf. Deterministic pure function of its arguments."""
    s_counts = _partition_counts(leaf.shape, leaf.src_spec, src, leaf.name)
    t_counts = _partition_counts(leaf.shape, leaf.dst_spec, dst, leaf.name)
    nbytes = leaf.bytes

    s_shards = 1
    for c in s_counts:
        s_shards *= c
    t_shards = 1
    for c in t_counts:
        t_shards *= c
    r_src = max(1, src.n_devices // max(1, s_shards))
    r_dst = max(1, dst.n_devices // max(1, t_shards))

    # resident fraction under the aligned linear-device mapping: the
    # share of each target shard already on its source-aligned device
    resident_frac = Fraction(1)
    for s, t in zip(s_counts, t_counts):
        resident_frac *= _aligned_overlap(s, t)
    need_total = nbytes * r_dst
    resident = int(nbytes * resident_frac * min(r_src, r_dst))
    same_layout = (s_counts == t_counts
                   and tuple(leaf.src_spec) == tuple(leaf.dst_spec)
                   and src.mesh_axes == dst.mesh_axes
                   and src.process_count == dst.process_count)
    if same_layout:
        resident = need_total

    bytes_slice = max(0, need_total - resident)
    bytes_gather = max(bytes_slice, nbytes * dst.n_devices - resident)
    bytes_host = nbytes + need_total  # up to host, back down; no credit

    if force_host:
        action, moved = HOST_FALLBACK, bytes_host
    elif same_layout:
        action, moved = KEEP, 0
    else:
        refines = all(t % s == 0 for s, t in zip(s_counts, t_counts))
        if refines:
            # every target shard is a contiguous slice of one source
            # shard: point-to-point slice exchange reaches the bound
            action, moved = SLICE_EXCHANGE, bytes_slice
        else:
            action, moved = ALLGATHER_SHARD, bytes_gather
    return LeafPlan(
        name=leaf.name, action=action, bytes_leaf=nbytes,
        bytes_moved=moved, bytes_lower_bound=bytes_slice,
        bytes_slice=bytes_slice, bytes_gather=bytes_gather,
        bytes_host=bytes_host, src_spec=tuple(leaf.src_spec),
        dst_spec=tuple(leaf.dst_spec))


def plan_reshard(src: Placement, dst: Placement,
                 leaves: Sequence[LeafLayout], *,
                 force_host: bool = False) -> ReshardPlan:
    """The planner entry point: validate both placements, plan every
    leaf, return the deterministic plan. `force_host=True` models the
    legacy gather-everything-to-host path (PR 6's lockstep host
    checkpoints) so its cost is comparable on the same scale — the
    engine itself only emits it for non-coexisting mesh pairs."""
    src.validate()
    dst.validate()
    plans = tuple(plan_leaf(leaf, src, dst, force_host=force_host)
                  for leaf in leaves)
    return ReshardPlan(src=src, dst=dst, leaves=plans)
