"""Resharding executors — the two ways a `planner.ReshardPlan` runs.

Layer 2 of the portable resharding engine (ROADMAP; arXiv:2112.01075):

- **live path** (`reshard_net_live`, used by `set_mesh` re-placement):
  source and target meshes coexist in this runtime. On the SAME device
  set the transfer is one jitted identity with `out_shardings` — a
  compiled collective program (its signature is frozen as the stage-3
  `reshard/live_transpose_2x4` entry); across device sets it is
  `jax.device_put`, XLA's point-to-point resharding transfer. Either
  way the move executes the plan's per-leaf actions without a host hop.
- **checkpoint path** (`checkpoint_template`, used by
  `ShardedCheckpointer.restore(net, target_mesh=...)`): the source mesh
  is gone; the plan maps checkpoint slices to target shards and orbax
  reads ONLY the byte ranges each target process's addressable shards
  need — `slice_exchange` becomes a sliced disk read, never a full-tree
  host materialization on a spanning mesh.

Both paths put the plan on the record before moving a byte: a
`reshard_plan` telemetry event with the planner summary, then a
`reshard` span carrying achieved `bytes_moved` against the plan's
`bytes_lower_bound` — the audit trail the elastic timeline test and the
CLI dry-run read back.

jax imports stay inside functions (the module is importable under
graftlint's no-jax stubs; the pure planner never needs it).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

from deeplearning4j_tpu.reshard.planner import (
    LeafLayout,
    Placement,
    ReshardPlan,
    plan_reshard,
)


@dataclass(frozen=True)
class SpecBox:
    """A partition-spec tuple wrapped as a pytree LEAF (a bare tuple
    would flatten); spec trees built from these stay congruent with the
    value trees they describe."""

    spec: tuple


_REPL = SpecBox(())


# ------------------------------------------------------------ placements

def mesh_placement(mesh, axes=None, *, zero1: bool = False) -> Placement:
    """A `planner.Placement` for a live Mesh (+ role map). `axes` is the
    set_mesh role->axis dict; None defaults to the data role on a 'data'
    axis when the mesh has one."""
    mesh_axes = {str(a): int(s) for a, s in mesh.shape.items()}
    if axes is None:
        axes = {"data": "data"} if "data" in mesh_axes else {}
    processes = len({d.process_index for d in mesh.devices.flat})
    return Placement.of(mesh_axes, dict(axes), process_count=processes,
                        zero1=zero1)


def net_placement(net) -> Placement:
    """The placement a network container currently trains under —
    `Placement.solo()` for an unplaced net."""
    mesh = getattr(net, "_mesh", None)
    if mesh is None:
        return Placement.solo()
    return mesh_placement(mesh, getattr(net, "_mesh_axes", None),
                          zero1=bool(getattr(net, "_zero1", False)))


# ------------------------------------------------------------ spec trees

def _rule_spec(name: str, placement: Placement, rules) -> tuple:
    """The partition-spec tuple one param name resolves to under the
    placement's mesh — the pure twin of `tensor_parallel.sharding_for`
    (replicated when no rule matches or a named axis is absent/size-1)."""
    sizes = placement.axis_sizes
    for pat, spec in rules or ():
        if re.match(pat, name):
            entries = tuple(spec)
            if all(not isinstance(ax, str)
                   or (ax in sizes and sizes[ax] > 1)
                   for ax in entries):
                return tuple(ax if isinstance(ax, str) else None
                             for ax in entries)
            break
    return ()


def param_spec_tree(params, placement: Placement, rules):
    """Dict-walk the param tree into a congruent tree of SpecBox leaves."""
    def walk(tree, prefix=""):
        out = {}
        for k, v in tree.items():
            name = f"{prefix}{k}"
            if isinstance(v, dict):
                out[k] = walk(v, name + "/")
            else:
                out[k] = SpecBox(_rule_spec(name, placement, rules))
        return out

    return walk(params)


def opt_spec_tree(opt_state, params, pspecs, placement: Placement):
    """Spec tree for the optimizer state: param-shaped subtrees mirror
    the param placement (TP/EP moments travel with their params); under
    zero1 every leaf shards its leading dim over the data axis when
    divisible (the exact `nn/training.zero1_opt_shardings` rule);
    counts/scalars stay replicated."""
    import jax

    if opt_state is None:
        return None
    data_ax = placement.axis_for("data")
    if placement.zero1 and data_ax is not None \
            and placement.axis_sizes.get(data_ax, 1) > 1:
        n = placement.axis_sizes[data_ax]

        def leaf(x):
            shape = getattr(x, "shape", ())
            if len(shape) >= 1 and shape[0] >= n and shape[0] % n == 0:
                return SpecBox((data_ax,) + (None,) * (len(shape) - 1))
            return _REPL

        return jax.tree.map(leaf, opt_state)

    ref = jax.tree.structure(params)

    def is_param_shaped(x):
        try:
            return jax.tree.structure(x) == ref
        except Exception:
            return False

    def sub(x):
        return pspecs if is_param_shaped(x) else jax.tree.map(
            lambda _: _REPL, x)

    return jax.tree.map(sub, opt_state, is_leaf=is_param_shaped)


def replicated_spec_tree(tree):
    import jax

    return jax.tree.map(lambda _: _REPL, tree) if tree is not None else None


def shardings_from_specs(spec_tree, mesh):
    """SpecBox tree -> NamedSharding tree on `mesh`."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if spec_tree is None:
        return None
    return jax.tree.map(
        lambda box: NamedSharding(mesh, P(*box.spec) if box.spec else P()),
        spec_tree)


# -------------------------------------------------------------- layouts

def _named_leaves(tree, spec_tree, prefix):
    """Aligned (name, value_leaf, spec_tuple) triples for one tree."""
    import jax

    if tree is None:
        return []
    vals, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = jax.tree.leaves(spec_tree)
    assert len(vals) == len(specs), "spec tree lost congruence"
    out = []
    for (path, leaf), box in zip(vals, specs):
        name = prefix + jax.tree_util.keystr(path)
        out.append((name, leaf, box.spec))
    return out


def build_layouts(trees: dict, src_specs: dict, dst_specs: dict):
    """-> list[LeafLayout] across named trees ({"params": ..., ...});
    leaves without a shape (python scalars) are skipped — they ride the
    meta/host path and move no device bytes."""
    layouts = []
    for key, tree in trees.items():
        src = _named_leaves(tree, src_specs[key], key)
        dst = _named_leaves(tree, dst_specs[key], key)
        for (name, leaf, s_spec), (_, _, d_spec) in zip(src, dst):
            shape = tuple(getattr(leaf, "shape", ()) or ())
            dtype = getattr(leaf, "dtype", None)
            itemsize = getattr(dtype, "itemsize", 0) or 0
            if not shape and not itemsize:
                continue
            layouts.append(LeafLayout(
                name=name, shape=shape, itemsize=itemsize or 1,
                src_spec=s_spec, dst_spec=d_spec))
    return layouts


# -------------------------------------------------------- net-level plan

def plan_for_placements(net, src_pl: Placement, dst_pl: Placement, *,
                        tp_rules=None):
    """Pure planning half: (plan, dst param spec tree, dst opt spec
    tree) for moving `net`'s params + optimizer state between two
    placements. No target mesh/devices needed — the CLI dry-run plans a
    checkpoint->anywhere move on a fake mesh."""
    from deeplearning4j_tpu.parallel.tensor_parallel import resolve_rules

    src_roles = dict(src_pl.roles)
    dst_roles = dict(dst_pl.roles)
    src_rules = resolve_rules(src_roles, tp_rules) if src_roles else []
    dst_rules = resolve_rules(dst_roles, tp_rules) if dst_roles else []

    p_src = param_spec_tree(net.params, src_pl, src_rules)
    p_dst = param_spec_tree(net.params, dst_pl, dst_rules)
    o_src = opt_spec_tree(net.opt_state, net.params, p_src, src_pl)
    o_dst = opt_spec_tree(net.opt_state, net.params, p_dst, dst_pl)
    trees = {"params": net.params}
    src_specs = {"params": p_src}
    dst_specs = {"params": p_dst}
    if net.opt_state is not None:
        trees["opt_state"] = net.opt_state
        src_specs["opt_state"] = o_src
        dst_specs["opt_state"] = o_dst
    plan = plan_reshard(src_pl, dst_pl, build_layouts(trees, src_specs,
                                                      dst_specs))
    return plan, p_dst, o_dst


def plan_net_reshard(net, dst_mesh, dst_axes=None, *,
                     src: Optional[Placement] = None,
                     zero1: Optional[bool] = None, tp_rules=None):
    """Plan moving `net`'s params + optimizer state from their current
    (or given `src`) placement onto `dst_mesh`/`dst_axes`. Returns
    (plan, param_shardings, opt_shardings) with the sharding trees built
    on the target mesh — everything both executors need."""
    src_pl = src if src is not None else net_placement(net)
    zero1 = bool(getattr(net, "_zero1", False)) if zero1 is None else zero1
    dst_pl = mesh_placement(dst_mesh, dst_axes, zero1=zero1)
    plan, p_dst, o_dst = plan_for_placements(net, src_pl, dst_pl,
                                             tp_rules=tp_rules)
    return (plan, shardings_from_specs(p_dst, dst_mesh),
            shardings_from_specs(o_dst, dst_mesh))


# ------------------------------------------------------------- live path

def _same_device_set(tree, mesh) -> bool:
    import jax

    target = set(mesh.devices.flat)
    for leaf in jax.tree.leaves(tree):
        sh = getattr(leaf, "sharding", None)
        if sh is None or set(getattr(sh, "device_set", ())) != target:
            return False
    return True


def live_transfer(tree, shardings, mesh):
    """Move one pytree onto its target shardings: a compiled collective
    identity when the leaves already live on exactly the target mesh's
    devices, `jax.device_put` (XLA's resharding transfer) otherwise."""
    import jax

    if tree is None or shardings is None:
        return tree
    if _same_device_set(tree, mesh):
        # one-shot placement work, not a per-step path (same contract as
        # the pipeline-placement jit in parallel/placement.py)
        return jax.jit(lambda t: t, out_shardings=shardings)(tree)  # graftlint: disable=G005
    return jax.tree.map(jax.device_put, tree, shardings)


def live_identity(shardings):
    """The jit'd collective-identity transfer for a fixed target — the
    traceable entry the stage-3 collective audit freezes."""
    import jax

    return jax.jit(lambda t: t, out_shardings=shardings)  # graftlint: disable=G005


def reshard_net_live(net, dst_mesh, dst_axes=None, *, tp_rules=None,
                     src: Optional[Placement] = None):
    """set_mesh re-placement: plan, record, and execute the live move of
    `net.params` (+ param-shaped optimizer subtrees) onto the target
    mesh. Returns the plan (already emitted as telemetry)."""
    from deeplearning4j_tpu.parallel.placement import _map_param_shaped
    from deeplearning4j_tpu.telemetry import get_default as _telemetry

    plan, p_sh, _o_sh = plan_net_reshard(net, dst_mesh, dst_axes, src=src,
                                         zero1=False, tp_rules=tp_rules)
    rec = _telemetry()
    rec.event("reshard_plan", path="live", **plan.summary())
    with rec.span("reshard", path="live", bytes_moved=plan.bytes_moved,
                  bytes_lower_bound=plan.bytes_lower_bound):
        net.params = live_transfer(net.params, p_sh, dst_mesh)
        if net.opt_state is not None:
            net.opt_state = _map_param_shaped(
                net.opt_state, net.params,
                lambda t: live_transfer(t, p_sh, dst_mesh))
    return plan


# ------------------------------------------------------- checkpoint path

def checkpoint_template(net, src_placement: Placement, dst_mesh,
                        dst_axes=None, *, zero1: Optional[bool] = None,
                        tp_rules=None):
    """The restore-side executor input: (plan, abstract_tree) where the
    abstract {params, opt_state, state} tree carries TARGET shardings —
    handed to orbax, which then reads only the shard slices this
    process's addressable devices need (slice_exchange as a sliced disk
    read; no full-tree host materialization on spanning meshes)."""
    import jax

    from jax.sharding import NamedSharding, PartitionSpec as P

    plan, p_sh, o_sh = plan_net_reshard(net, dst_mesh, dst_axes,
                                        src=src_placement, zero1=zero1,
                                        tp_rules=tp_rules)
    repl = NamedSharding(dst_mesh, P())

    def abstract(x, sharding):
        return jax.ShapeDtypeStruct(getattr(x, "shape", ()),
                                    getattr(x, "dtype", None),
                                    sharding=sharding)

    tmpl = {
        "params": jax.tree.map(abstract, net.params, p_sh),
        "opt_state": (jax.tree.map(abstract, net.opt_state, o_sh)
                      if net.opt_state is not None else None),
        "state": (jax.tree.map(lambda x: abstract(x, repl), net.state)
                  if net.state is not None else net.state),
    }
    return plan, tmpl
