"""Portable resharding engine: train on one mesh, restore and serve on
any other (ROADMAP; arXiv:2112.01075 + the zero1 composition of
arXiv:2004.13336).

Three layers:

1. `reshard.planner` — a PURE function mapping (source placement,
   target placement, leaf layouts) to a deterministic per-leaf plan
   (keep / slice_exchange / allgather_shard / host_fallback) with a
   bytes-moved cost model and its lower bound. Stdlib-only, rank- and
   clock-independent: every process derives the identical plan.
2. `reshard.executor` — the live path (jitted collective identity /
   device_put when the meshes coexist: `set_mesh` re-placement, elastic
   re-form on survivors) and the checkpoint path (target-sharded orbax
   templates: each process reads only the shard slices it needs —
   `ShardedCheckpointer.restore(net, target_mesh=...)`).
3. integration — `parallel/placement.py` routes re-placement of an
   already-placed net through the plans, `distributed/elastic.py`
   restores re-formed fleets through the planner, and
   `serving/engine.py` accepts checkpoints written under any training
   mesh.

Layer 0, one level up: `reshard.search` — the automatic placement
search. It enumerates every `Placement` a fleet shape admits, prunes
with the SAME `PlacementError` validation, and ranks the survivors
with a pure-stdlib per-step cost model; `search_placement(...).winner`
feeds `set_mesh` unmodified, the CLI `plan` subcommand prints the
ranked table, and the elastic supervisor re-plans with it at N -> N'.

Importing this package is jax-free (planner and search are pure
stdlib; executor imports jax lazily) so tools and the graftlint stubs
stay cheap.
"""

from deeplearning4j_tpu.reshard.planner import (  # noqa: F401
    ACTIONS,
    ALLGATHER_SHARD,
    HOST_FALLBACK,
    KEEP,
    SLICE_EXCHANGE,
    LeafLayout,
    LeafPlan,
    Placement,
    PlacementError,
    ReshardPlan,
    plan_leaf,
    plan_reshard,
)
from deeplearning4j_tpu.reshard.search import (  # noqa: F401
    BUILTIN_PROFILES,
    FleetShape,
    ModelProfile,
    Objective,
    ParamLeaf,
    ScoredCandidate,
    SearchError,
    SearchResult,
    enumerate_placements,
    profile_net,
    score_placement,
    search_placement,
)
