"""Automatic placement search — the cost model picks the fastest mesh,
not just moves to it.

The ROADMAP item this delivers: placements were hand-specified, so every
fleet shape shipped whatever dp x tp x pp x sp x ep assignment a human
guessed — the one knob with the largest step-time leverage was untuned.
The planner's exact bytes-moved machinery (arXiv:2112.01075) already
knows how to *cost* a layout; this module turns that discipline one
level up: enumerate every `planner.Placement` a fleet shape admits,
prune the illegal ones with the SAME `PlacementError` validation the
reshard planner uses (zero1 x TP, non-dividing axes, role on a missing
axis — feasibility comes for free), and rank the survivors with a
pure-stdlib per-step cost model. arXiv:2004.13336's automatic
weight-update sharding is the special case we already ship (zero1 on
the data axis); the search generalizes it to the whole role vocabulary.
The sweep -> score -> freeze -> gate shape is the kerneltune (PR 8)
discipline applied to the mesh itself.

Like the planner, everything on the search path is pure stdlib and pure
data:

- no jax import (`tests/test_placement_search.py` proves the module
  plans under a poisoned `jax`);
- no dependence on rank or clock — every fleet member computes the
  byte-identical ranking (asserted under simulated `process_index`
  0 vs 1, the same discipline as `plan_reshard`), which is what lets
  the elastic re-plan run on every worker without coordination.

## The cost model (exact rationals; bytes and bytes-equivalents)

For a candidate with role sizes dp/tp/pp/sp/ep, a model profile with
param leaves L (name, shape, itemsize), TP/EP rules R, and an
`Objective` with global batch B and per-device HBM budget H:

| term | formula |
|---|---|
| `tp_shards(l)` | product of the rule-named role sizes sharding leaf l (a rule activates only when ALL its named roles are >1 — the `tensor_parallel.sharding_for` semantics; a named dim that does not divide is a `PlacementError` prune) |
| `params_dev` | sum_l bytes(l)/tp_shards(l) / pp  (pipeline stages split the layer stack) |
| `grads_dev` | params_dev |
| `moments_dev` | 2 x params_dev / (dp if zero1 else 1)  (the 2004.13336 weight-update shard) |
| `act_micro` | (B/dp/n_micro) x (seq_len/sp) x act_width x 4  — the activation envelope of ONE microbatch (act_width = sum of last dims of ndim>=2 leaves); n_micro = microbatch_factor x pp when pp>1 else 1 |
| `memory_dev` | params_dev + grads_dev + moments_dev + act_micro x max(pp, 1)  — rejected when > H ("no feasible placement fits the HBM budget" when every candidate dies here) |
| dp collective | 2 x G x (dp-1)/dp with G = grads_dev (ring allreduce), + G x (dp-1)/dp more under zero1 (the param all-gather) |
| tp collective | 2 x (n_layers/pp) x act_micro x (tp-1)/tp x n_micro  (two activation allreduces per layer) |
| sp collective | (n_layers/pp) x act_micro x (sp-1)/sp x n_micro  (ring K/V hops) |
| ep collective | 2 x (n_layers/pp) x act_micro x (ep-1)/ep x n_micro  (dispatch + combine all_to_all) |
| pp transfer | act_micro x n_micro x (pp-1)/pp  (stage-boundary sends) |
| `bubble` | (pp-1)/(n_micro+pp-1) x compute_dev x compute_weight  (the GPipe bubble idles this device's own work) |
| `idle` | (compute_dev - C/n_devices) x compute_weight — the penalty for an axis that divides no work (a model axis whose rules shard nothing leaves its devices redundant); C = 2 x B x seq_len x param_bytes, compute_dev = C / (dp x pp x sp x effective tp x effective ep) |

    score = collective_bytes + bubble + idle        (lower is better)

`Objective(step="forward")` scores the inference surface instead: the
gradient and optimizer terms vanish and the activation collectives run
once per step instead of twice (no backward traversal) — the surface
the predicted-vs-measured bench gate measures, since this container
cannot execute TP train steps (the pre-existing donation-alias class).
`compute_weight` (default 1/16, roughly MXU flops per HBM byte) converts
compute-shaped terms into wire-byte equivalents; memory gates
feasibility but does not enter the score. The score is a RANKING model,
not a latency predictor — the bench's predicted-vs-measured gate
(bench.py `placement_search`) asserts rank agreement on the
2x2/3x2/2x4 device-grid matrix, never absolute ms.

## Surfaces

- `search_placement(net_or_profile, fleet, objective=...)` -> ranked
  `SearchResult`; `result.winner` is a `planner.Placement` that
  `net.set_mesh(...)` consumes directly (parallel/placement.py builds
  the mesh and role map from it).
- CLI `plan --model mlp --fleet 2x4` (cli/driver.py) — the dry-run
  top-k table + PLAN artifact; builtin profiles keep it jax-free.
- `distributed/elastic.searched_global_mesh` — the elastic re-plan: a
  re-formed generation searches the placement for its OWN fleet shape
  instead of inheriting the dead generation's roles.

Every surface emits a typed `placement_search` telemetry event
(`emit_search_event`) so the candidates considered, the prunes, and the
winner's score breakdown are on the record before any mesh is built.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from fractions import Fraction
from typing import Optional, Sequence, Tuple

from deeplearning4j_tpu.reshard.planner import (
    Placement,
    PlacementError,
    VALID_ROLES,
)

# canonical axis order of every candidate mesh (axes are NAMED by their
# role, the CLI `--mesh data=2,model=2` convention, so rule specs and
# set_mesh role maps line up for free)
ROLE_ORDER = ("data", "model", "pipe", "seq", "expert")

ACT_ITEMSIZE = 4  # activations modeled f32 (the training envelope)


class SearchError(RuntimeError):
    """No feasible placement survived the prune (e.g. nothing fits the
    per-device HBM budget); carries the per-candidate reasons."""


# ------------------------------------------------------------------ input

@dataclass(frozen=True)
class FleetShape:
    """A fleet as the launcher sees it: N processes x K devices each."""

    process_count: int
    devices_per_process: int

    def __post_init__(self):
        if self.process_count < 1 or self.devices_per_process < 1:
            raise ValueError(f"bad fleet shape {self.describe()}")

    @classmethod
    def parse(cls, spec: str) -> "FleetShape":
        """'2x4' -> FleetShape(2, 4); '8' -> FleetShape(1, 8)."""
        parts = str(spec).lower().split("x")
        if len(parts) == 1:
            return cls(1, int(parts[0]))
        if len(parts) != 2:
            raise ValueError(f"bad --fleet spec {spec!r}; expected PxK")
        return cls(int(parts[0]), int(parts[1]))

    @property
    def n_devices(self) -> int:
        return self.process_count * self.devices_per_process

    def describe(self) -> str:
        return f"{self.process_count}x{self.devices_per_process}"


@dataclass(frozen=True)
class ParamLeaf:
    """One param-tree leaf as pure data."""

    name: str
    shape: Tuple[int, ...]
    itemsize: int = 4

    @property
    def bytes(self) -> int:
        n = self.itemsize
        for d in self.shape:
            n *= d
        return n


@dataclass(frozen=True)
class ModelProfile:
    """Everything the cost model needs to know about a net, as pure
    data: its param leaves, layer count, sequence length (1 for
    non-sequence models), the roles its conf/container can actually
    run (`supports`), and the TP/EP placement rules with ROLE-named
    spec entries (plain tuples — `tensor_parallel`'s PartitionSpec
    rules convert via `tuple(spec)`)."""

    name: str
    leaves: Tuple[ParamLeaf, ...]
    n_layers: int
    seq_len: int = 1
    supports: Tuple[str, ...] = ("data", "model")
    rules: Tuple[Tuple[str, Tuple[Optional[str], ...]], ...] = ()

    @property
    def param_bytes(self) -> int:
        return sum(l.bytes for l in self.leaves)

    @property
    def activation_width(self) -> int:
        return sum(l.shape[-1] for l in self.leaves if len(l.shape) >= 2)


@dataclass(frozen=True)
class Objective:
    """What the search optimizes under: the per-step workload shape and
    the per-device memory budget. `zero1_options` widens the candidate
    set with weight-update-sharded variants of the pure-dp placements;
    `compute_weight` converts compute-shaped terms (bubble, idle
    devices) into wire-byte equivalents. ``step`` picks the cost
    surface: "train" (the default — gradient allreduce, moments,
    fwd+bwd activation collectives) or "forward" (the inference/serving
    placement: no gradient or optimizer terms, activation collectives
    halved — what the predicted-vs-measured bench gate measures, since
    this container cannot execute TP train steps)."""

    global_batch: int = 32
    hbm_bytes_per_device: int = 16 << 30
    microbatch_factor: int = 2
    compute_weight: Fraction = Fraction(1, 16)
    zero1_options: Tuple[bool, ...] = (False, True)
    step: str = "train"

    def __post_init__(self):
        if self.step not in ("train", "forward"):
            raise ValueError(f"objective step must be 'train' or "
                             f"'forward' (got {self.step!r})")

    def to_json(self) -> dict:
        return {"global_batch": self.global_batch,
                "hbm_bytes_per_device": self.hbm_bytes_per_device,
                "microbatch_factor": self.microbatch_factor,
                "compute_weight": float(self.compute_weight),
                "zero1_options": list(self.zero1_options),
                "step": self.step}


# ----------------------------------------------------------------- output

@dataclass(frozen=True)
class ScoredCandidate:
    """One feasible placement with its exact-rational score breakdown."""

    placement: Placement
    score: Fraction
    memory_bytes: Fraction          # per-device high-water estimate
    collective_bytes: Fraction      # per-device wire bytes per step
    bubble_cost: Fraction           # pp bubble, bytes-equivalent
    idle_cost: Fraction             # redundant-axis penalty, bytes-equiv
    params_bytes: Fraction
    moments_bytes: Fraction
    activation_bytes: Fraction

    def describe(self) -> str:
        return self.placement.describe()

    def to_json(self) -> dict:
        return {"placement": self.placement.to_json(),
                "describe": self.describe(),
                "score": float(self.score),
                "memory_bytes": float(self.memory_bytes),
                "collective_bytes": float(self.collective_bytes),
                "bubble_cost": float(self.bubble_cost),
                "idle_cost": float(self.idle_cost),
                "params_bytes": float(self.params_bytes),
                "moments_bytes": float(self.moments_bytes),
                "activation_bytes": float(self.activation_bytes)}


@dataclass(frozen=True)
class SearchResult:
    """The ranked search output. `candidates` is best-first;
    `winner` is the top candidate's `Placement` — the value
    `net.set_mesh(...)` consumes unmodified."""

    fleet: FleetShape
    profile_name: str
    objective: Objective
    candidates: Tuple[ScoredCandidate, ...]
    pruned: Tuple[Tuple[str, str], ...]  # (placement description, reason)

    @property
    def winner(self) -> Placement:
        return self.candidates[0].placement

    @property
    def best(self) -> ScoredCandidate:
        return self.candidates[0]

    @property
    def n_considered(self) -> int:
        return len(self.candidates) + len(self.pruned)

    def to_json(self) -> dict:
        return {"fleet": self.fleet.describe(),
                "profile": self.profile_name,
                "objective": self.objective.to_json(),
                "candidates": [c.to_json() for c in self.candidates],
                "pruned": [list(p) for p in self.pruned]}

    def table_lines(self, top: int = 5) -> list:
        """The CLI dry-run table: rank, placement, score breakdown."""
        out = [f"# placement search: {self.profile_name} on fleet "
               f"{self.fleet.describe()} ({self.fleet.n_devices} devices)"
               f" — {len(self.candidates)} feasible, "
               f"{len(self.pruned)} pruned"]
        out.append(f"# {'rank':>4}  {'placement':<34} {'score':>12} "
                   f"{'mem/dev':>10} {'coll B/step':>12} {'bubble':>10} "
                   f"{'idle':>10}")
        for i, c in enumerate(self.candidates[:top], start=1):
            out.append(
                f"# {i:>4}  {c.describe():<34} {float(c.score):>12.0f} "
                f"{float(c.memory_bytes):>10.0f} "
                f"{float(c.collective_bytes):>12.0f} "
                f"{float(c.bubble_cost):>10.0f} "
                f"{float(c.idle_cost):>10.0f}")
        for desc, reason in self.pruned[:top]:
            out.append(f"#  pruned {desc:<32} {reason}")
        return out


# ------------------------------------------------------------ enumeration

def _role_factorizations(n: int, roles: Sequence[str]):
    """Every assignment {role: size>=1} with product == n (all devices
    used), deterministic order."""
    roles = list(roles)

    def rec(i, remaining):
        if i == len(roles) - 1:
            yield {roles[i]: remaining}
            return
        d = 1
        while d <= remaining:
            if remaining % d == 0:
                for rest in rec(i + 1, remaining // d):
                    yield {roles[i]: d, **rest}
            d += 1

    yield from rec(0, n)


def enumerate_placements(fleet: FleetShape, *,
                         roles: Sequence[str] = ROLE_ORDER,
                         zero1_options: Tuple[bool, ...] = (False, True)):
    """-> (candidates, pruned): every `Placement` the fleet shape
    admits over `roles` (axes named by role, sizes multiplying to the
    full device count), plus the (description, reason) prunes. The
    feasibility filter IS `planner.Placement.of` — zero1 x TP, role on
    a missing axis, process counts that do not divide all raise
    `PlacementError` there and cost nothing here. Process-spanning
    fleets additionally prune non-data roles (the set_mesh guard:
    cross-process model/pipe/expert/seq placement is still host-side
    device_puts — ARCHITECTURE §Distributed runtime)."""
    bad = set(roles) - set(VALID_ROLES)
    if bad:
        raise ValueError(f"unknown roles {sorted(bad)}; valid: "
                         f"{VALID_ROLES}")
    roles = [r for r in ROLE_ORDER if r in set(roles)]
    candidates, pruned = [], []
    for sizes in _role_factorizations(fleet.n_devices, roles):
        mesh_axes = {r: s for r, s in sizes.items() if s > 1}
        if not mesh_axes:
            mesh_axes = {"data": 1}
        role_map = {r: r for r in mesh_axes}
        desc_sizes = "x".join(str(s) for s in mesh_axes.values())
        if fleet.process_count > 1 and set(mesh_axes) - {"data"}:
            pruned.append((
                f"{desc_sizes} ({','.join(mesh_axes)})",
                "process-spanning mesh supports the 'data' role only "
                "(set_mesh guard — ARCHITECTURE §Distributed runtime)"))
            continue
        zero1_eligible = not (set(mesh_axes) - {"data"})
        for z in zero1_options:
            if z and not zero1_eligible:
                continue  # Placement.of would refuse; skip silently —
                # the un-zero1'd twin of this assignment is the candidate
            try:
                candidates.append(Placement.of(
                    mesh_axes, role_map,
                    process_count=fleet.process_count, zero1=z))
            except PlacementError as exc:
                pruned.append((desc_sizes, str(exc)))
    return candidates, pruned


# ---------------------------------------------------------------- scoring

def _role_sizes(placement: Placement) -> dict:
    sizes = placement.axis_sizes
    return {role: sizes.get(ax, 1) for role, ax in placement.roles}


def _leaf_shards(leaf: ParamLeaf, sizes: dict, rules) -> int:
    """How many ways the candidate's rules shard this leaf — the
    `tensor_parallel.sharding_for` semantics on pure data: first
    matching pattern wins; it activates only when EVERY role it names
    has size > 1; an activated role whose dim does not divide raises
    `PlacementError` (the prune)."""
    for pat, spec in rules or ():
        if re.match(pat, leaf.name):
            entries = tuple(spec)
            named = [r for r in entries if isinstance(r, str)]
            if not all(sizes.get(r, 1) > 1 for r in named):
                break  # replicated (a named role is absent/1)
            shards = 1
            for d, r in enumerate(entries):
                if not isinstance(r, str):
                    continue
                n = sizes[r]
                if d >= len(leaf.shape) or leaf.shape[d] % n:
                    raise PlacementError(
                        f"leaf {leaf.name!r}: dim {d} of {leaf.shape} "
                        f"does not divide over {n}-way role {r!r}")
                shards *= n
            return shards
    return 1


def score_placement(profile: ModelProfile, placement: Placement,
                    objective: Objective,
                    fleet: FleetShape) -> ScoredCandidate:
    """Score one feasible placement (exact rationals throughout).
    Raises `PlacementError` for net-level infeasibility (non-dividing
    leaf dims, batch/microbatch/sequence that do not divide, HBM
    budget exceeded) — the caller records it as a prune."""
    sizes = _role_sizes(placement)
    dp = sizes.get("data", 1)
    tp = sizes.get("model", 1)
    pp = sizes.get("pipe", 1)
    sp = sizes.get("seq", 1)
    ep = sizes.get("expert", 1)
    for role, n in (("model", tp), ("pipe", pp), ("seq", sp),
                    ("expert", ep)):
        if n > 1 and role not in profile.supports:
            raise PlacementError(
                f"profile {profile.name!r} does not support the "
                f"{role!r} role (supports: {profile.supports})")

    B = objective.global_batch
    if B % dp:
        raise PlacementError(
            f"global batch {B} does not divide over the {dp}-way data "
            "axis")
    n_micro = objective.microbatch_factor * pp if pp > 1 else 1
    rows = B // dp
    if rows % n_micro:
        raise PlacementError(
            f"per-replica batch {rows} does not divide into {n_micro} "
            "microbatches")
    if pp > 1 and profile.n_layers % pp:
        raise PlacementError(
            f"{profile.n_layers} layers do not divide over {pp} "
            "pipeline stages")
    if sp > 1 and profile.seq_len % sp:
        raise PlacementError(
            f"sequence length {profile.seq_len} does not divide over "
            f"the {sp}-way seq axis")

    # --- per-device memory (params + grads + moments + activations)
    train = objective.step == "train"
    sharded_roles = set()
    shard_bytes = Fraction(0)
    for leaf in profile.leaves:
        shards = _leaf_shards(leaf, sizes, profile.rules)
        if shards > 1:
            for pat, spec in profile.rules:
                if re.match(pat, leaf.name):
                    sharded_roles |= {r for r in spec
                                      if isinstance(r, str)}
                    break
        shard_bytes += Fraction(leaf.bytes, shards)
    params_dev = shard_bytes / pp
    grads_dev = params_dev if train else Fraction(0)
    moments_dev = (2 * params_dev / (dp if placement.zero1 and dp > 1
                                     else 1)
                   if train else Fraction(0))
    act_micro = (Fraction(rows, n_micro) * Fraction(profile.seq_len, sp)
                 * profile.activation_width * ACT_ITEMSIZE)
    memory_dev = (params_dev + grads_dev + moments_dev
                  + act_micro * max(pp, 1))
    if memory_dev > objective.hbm_bytes_per_device:
        raise PlacementError(
            f"memory estimate {float(memory_dev):.0f} B/device exceeds "
            f"the HBM budget {objective.hbm_bytes_per_device} B")

    # --- collective bytes per device per step; the forward surface has
    # no gradient traffic and runs the activation collectives once
    # (no backward re-traversal) — act_passes carries the halving
    layers_stage = Fraction(profile.n_layers, pp)
    act_passes = 2 if train else 1
    coll = Fraction(0)
    coll += 2 * grads_dev * Fraction(dp - 1, dp)            # grad ring
    if train and placement.zero1 and dp > 1:
        coll += params_dev * Fraction(dp - 1, dp)           # param gather
    tp_effective = tp > 1 and "model" in sharded_roles
    ep_effective = ep > 1 and "expert" in sharded_roles
    if tp_effective:
        coll += act_passes * layers_stage * act_micro \
            * Fraction(tp - 1, tp) * n_micro
    if sp > 1:
        coll += (Fraction(act_passes, 2) * layers_stage * act_micro
                 * Fraction(sp - 1, sp) * n_micro)
    if ep_effective:
        coll += act_passes * layers_stage * act_micro \
            * Fraction(ep - 1, ep) * n_micro
    if pp > 1:
        coll += (Fraction(act_passes, 2) * act_micro * n_micro
                 * Fraction(pp - 1, pp))                    # stage p2p

    # --- compute-shaped terms (bytes-equivalent via compute_weight)
    C = 2 * B * profile.seq_len * profile.param_bytes
    denom = dp * pp * sp * (tp if tp_effective else 1) \
        * (ep if ep_effective else 1)
    compute_dev = Fraction(C, denom)
    bubble = Fraction(0)
    if pp > 1:
        bubble = (Fraction(pp - 1, n_micro + pp - 1) * compute_dev
                  * objective.compute_weight)
    idle = ((compute_dev - Fraction(C, fleet.n_devices))
            * objective.compute_weight)

    return ScoredCandidate(
        placement=placement, score=coll + bubble + idle,
        memory_bytes=memory_dev, collective_bytes=coll,
        bubble_cost=bubble, idle_cost=idle, params_bytes=params_dev,
        moments_bytes=moments_dev, activation_bytes=act_micro)


# ----------------------------------------------------------------- search

def search_placement(net_or_profile, fleet, *, objective=None,
                     roles: Sequence[str] = ROLE_ORDER) -> SearchResult:
    """Enumerate, prune, score, and rank every placement `fleet`
    admits for the given net (or `ModelProfile`). Deterministic and
    rank-independent: the ranking is a pure function of
    (profile, fleet, objective)."""
    if isinstance(fleet, str):
        fleet = FleetShape.parse(fleet)
    profile = (net_or_profile if isinstance(net_or_profile, ModelProfile)
               else profile_net(net_or_profile))
    objective = objective or Objective()
    raw, pruned = enumerate_placements(
        fleet, roles=[r for r in roles if r in profile.supports
                      or r == "data"],
        zero1_options=objective.zero1_options)
    scored = []
    for placement in raw:
        try:
            scored.append(score_placement(profile, placement, objective,
                                          fleet))
        except PlacementError as exc:
            pruned.append((placement.describe(), str(exc)))
    if not scored:
        reasons = "; ".join(f"{d}: {r}" for d, r in pruned[:6])
        raise SearchError(
            f"no feasible placement for {profile.name!r} on fleet "
            f"{fleet.describe()} — every candidate was pruned "
            f"({reasons})")
    scored.sort(key=lambda c: (c.score, c.memory_bytes, c.describe()))
    return SearchResult(fleet=fleet, profile_name=profile.name,
                        objective=objective, candidates=tuple(scored),
                        pruned=tuple(pruned))


def emit_search_event(result: SearchResult, *, path: str,
                      search_ms: float, **fields) -> dict:
    """The typed `placement_search` telemetry event every search
    surface (CLI plan, elastic re-plan, bench) puts on the record:
    candidates considered, prunes, the winner's score breakdown, and
    the search wall time."""
    from deeplearning4j_tpu.telemetry.recorder import get_default

    best = result.best
    return get_default().event(
        "placement_search", path=path, fleet=result.fleet.describe(),
        profile=result.profile_name,
        candidates_considered=result.n_considered,
        candidates_feasible=len(result.candidates),
        pruned=len(result.pruned), winner=best.describe(),
        winner_score=float(best.score),
        winner_memory_bytes=float(best.memory_bytes),
        winner_collective_bytes=float(best.collective_bytes),
        winner_bubble_cost=float(best.bubble_cost),
        winner_idle_cost=float(best.idle_cost),
        search_ms=round(float(search_ms), 3), **fields)


# --------------------------------------------------------------- profiles

def profile_net(net, *, seq_len: Optional[int] = None,
                supports: Optional[Sequence[str]] = None,
                tp_rules=None, name: Optional[str] = None) -> ModelProfile:
    """A `ModelProfile` of a live network container (the impure
    boundary: reads param shapes and layer counts; initializes the net
    if needed). Rules default to the active `tensor_parallel`
    role-rule sets with role-named axes, converted to pure tuples."""
    if net.params is None:
        net.init()
    leaves = []

    def walk(tree, prefix=""):
        for k in tree:
            v = tree[k]
            if isinstance(v, dict):
                walk(v, prefix + str(k) + "/")
            else:
                leaves.append(ParamLeaf(
                    prefix + str(k),
                    tuple(int(d) for d in getattr(v, "shape", ()) or ()),
                    int(getattr(getattr(v, "dtype", None), "itemsize", 4)
                        or 4)))

    walk(net.params)
    if hasattr(net, "layer_vertices"):
        n_layers = len(net.layer_vertices)
    else:
        n_layers = len(net.layer_confs)
    if tp_rules is None:
        from deeplearning4j_tpu.parallel.tensor_parallel import \
            resolve_rules

        tp_rules = resolve_rules({"model": "model", "expert": "expert"})
    rules = tuple((pat, tuple(spec)) for pat, spec in tp_rules)
    return ModelProfile(
        name=name or type(net).__name__,
        leaves=tuple(leaves), n_layers=max(1, n_layers),
        seq_len=int(seq_len or 1),
        supports=tuple(supports or ("data", "model")),
        rules=rules)


# Built-in pure-data profiles: the CLI `plan` dry-run stays jax-free
# for named models (a laptop plans a pod placement without a backend).
# "mlp" mirrors the bench/cluster toy (3 dense layers); "lm" mirrors
# the tiny transformer the placement bench measures — its leaves are
# the REAL `models/transformer.transformer_lm` param tree at the bench
# dims, so the profile's divisibility prunes are the net's. d_model=80
# with 5 heads is deliberate: 80 admits tp 2/4/8 (and prunes tp 3/6 —
# the non-dividing-axis prune the 3x2 grid exercises), while 5 heads
# divide NO candidate tp, so every TP arm pays the head-resharding
# cost the collective term stands in for.
_TP_RULES = (
    (r".*_attn/Wqkv$", (None, "model")),
    (r".*_attn/bqkv$", ("model",)),
    (r".*_attn/Wo$", ("model", None)),
    (r".*_ff1/W$", (None, "model")),
    (r".*_ff1/b$", ("model",)),
    (r".*_ff2/W$", ("model", None)),
    (r"embed/W$", (None, "model")),
    (r"out/W$", (None, "model")),
    (r"out/b$", ("model",)),
)

_LM_V, _LM_D, _LM_FF, _LM_T, _LM_L = 80, 80, 160, 48, 2
_LM_H = 5  # heads: coprime to every candidate tp (see above)

BUILTIN_PROFILES = {
    "mlp": ModelProfile(
        name="mlp",
        leaves=(ParamLeaf("dense0/W", (48, 96)), ParamLeaf("dense0/b", (96,)),
                ParamLeaf("dense1/W", (96, 96)), ParamLeaf("dense1/b", (96,)),
                ParamLeaf("out/W", (96, 12)), ParamLeaf("out/b", (12,))),
        n_layers=3, seq_len=1, supports=("data", "model"),
        rules=((r".*dense\d/W$", (None, "model")),
               (r".*dense\d/b$", ("model",)))),
    "lm": ModelProfile(
        name="lm",
        leaves=tuple(
            [leaf for i in range(_LM_L) for leaf in (
                ParamLeaf(f"blk{i}_attn/Wqkv", (_LM_D, 3 * _LM_D)),
                ParamLeaf(f"blk{i}_attn/bqkv", (3 * _LM_D,)),
                ParamLeaf(f"blk{i}_attn/Wo", (_LM_D, _LM_D)),
                ParamLeaf(f"blk{i}_attn/bo", (_LM_D,)),
                ParamLeaf(f"blk{i}_ff1/W", (_LM_D, _LM_FF)),
                ParamLeaf(f"blk{i}_ff1/b", (_LM_FF,)),
                ParamLeaf(f"blk{i}_ff2/W", (_LM_FF, _LM_D)),
                ParamLeaf(f"blk{i}_ff2/b", (_LM_D,)),
                ParamLeaf(f"blk{i}_ln1/gamma", (_LM_D,)),
                ParamLeaf(f"blk{i}_ln1/beta", (_LM_D,)),
                ParamLeaf(f"blk{i}_ln2/gamma", (_LM_D,)),
                ParamLeaf(f"blk{i}_ln2/beta", (_LM_D,)))]
            + [ParamLeaf("embed/W", (_LM_V, _LM_D)),
               ParamLeaf("ln_f/gamma", (_LM_D,)),
               ParamLeaf("ln_f/beta", (_LM_D,)),
               ParamLeaf("out/W", (_LM_D, _LM_V)),
               ParamLeaf("out/b", (_LM_V,))]),
        n_layers=_LM_L, seq_len=_LM_T, supports=("data", "model"),
        rules=_TP_RULES),
}

# the profile the elastic supervisor ranks re-plans with when it has no
# model in-process (the data-role-only spanning constraint makes the
# fleet-level ranking exact for it anyway: dp coverage + zero1 choice)
GENERIC_PROFILE = BUILTIN_PROFILES["mlp"]
