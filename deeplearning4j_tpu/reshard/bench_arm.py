"""One measured arm of the `placement_search` bench (bench.py).

Run in its OWN process so each arm gets a fresh jax platform with
exactly the grid's virtual device count:

    python -m deeplearning4j_tpu.reshard.bench_arm '<spec json>'

The spec names the device count, the candidate `Placement` (JSON), and
the workload (the builtin "lm" profile's transformer dims + batch).
The arm builds the net, feeds the Placement to `set_mesh` UNMODIFIED —
the same integration contract tier-1 proves for training parity — and
times the forward step (warm, then `repeats` timed calls, median
reported). The forward step is the measured surface because this
container cannot execute TP train steps (the pre-existing
donation-alias XlaRuntimeError class the reshard matrix already
documents); the search side mirrors it with `Objective(step="forward")`
so predicted and measured rank the same quantity.

Prints one `RESULT {json}` line: {"placement", "ms_per_step",
"times_ms", "devices", "measured_bytes"} — plus "predicted_bytes" when
the spec carries the search's prediction (the parent passes it for the
WINNER arm only), in which case the arm also emits a typed
`cost_drift` reconciliation event (telemetry/costbook.py) — the parent
bench mode reads the measurement back.
"""

from __future__ import annotations

import json
import sys
import time


def run_arm(spec: dict) -> dict:
    from deeplearning4j_tpu.util.virtual_devices import ensure_cpu_devices

    ensure_cpu_devices(int(spec["devices"]))

    import numpy as np

    import jax

    from deeplearning4j_tpu.models.transformer import transformer_lm
    from deeplearning4j_tpu.reshard.planner import Placement
    from deeplearning4j_tpu.reshard.search import (
        _LM_D,
        _LM_FF,
        _LM_H,
        _LM_L,
        _LM_T,
        _LM_V,
    )

    placement = Placement.from_json(spec["placement"])
    batch = int(spec.get("batch", 48))
    repeats = int(spec.get("repeats", 8))

    net = transformer_lm(vocab_size=_LM_V, d_model=_LM_D, n_heads=_LM_H,
                         n_layers=_LM_L, d_ff=_LM_FF, max_length=_LM_T)
    net.init()
    net.set_mesh(placement)  # the searched winner's consumption contract

    rng = np.random.default_rng(int(spec.get("seed", 0)))
    toks = np.asarray(rng.integers(0, _LM_V, (batch, _LM_T)), np.int32)
    jax.block_until_ready(net.output(toks))  # compile + warm
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(net.output(toks))
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    from deeplearning4j_tpu.telemetry import costbook

    measured = costbook.measured_peak_bytes()
    result = {"placement": placement.describe(),
              "devices": int(spec["devices"]),
              "ms_per_step": round(times[len(times) // 2], 4),
              "times_ms": [round(t, 4) for t in times],
              "measured_bytes": int(measured)}
    predicted = float(spec.get("predicted_bytes") or 0.0)
    if predicted > 0:
        # cost-model calibration: reconcile the search's predicted
        # per-device bytes against this arm's measured peak (backend
        # memory_stats on TPU, live-array total on CPU) — a typed
        # `cost_drift` event lands on the shared telemetry record and
        # the measurement rides RESULT back to the parent bench mode.
        # The parent passes predicted_bytes for the WINNER arm only:
        # the control arm's memory model is a ranking penalty, not a
        # calibrated prediction, and must not pollute the drift record
        from deeplearning4j_tpu.telemetry.recorder import get_default

        costbook.reconcile(get_default(), int(predicted),
                           measured_bytes=measured, source="placement")
        result["predicted_bytes"] = predicted
    return result


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        sys.stderr.write("usage: bench_arm '<spec json>'\n")
        return 2
    result = run_arm(json.loads(argv[0]))
    print("RESULT " + json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
