"""Loss functions — ND4J `LossFunctions` equivalents.

The reference's output layers score via external ND4J LossFunctions (used by
BaseOutputLayer; SURVEY.md §2.1 L0 row). Names follow the reference's
LossFunction enum (MSE, XENT, MCXENT, NEGATIVELOGLIKELIHOOD, EXPLL,
RMSE_XENT, SQUARED_LOSS, RECONSTRUCTION_CROSSENTROPY, CUSTOM).

Every loss here is a pure function of (labels, preactivation-or-activation)
suitable for jax.grad; losses that fuse with their canonical activation
(softmax+MCXENT, sigmoid+XENT) provide a numerically-stable fused path on
logits — the TPU-native improvement over computing on activated outputs.

All losses support an optional broadcastable `mask` (the reference's
per-timestep label masking — MultiLayerNetwork.setLayerMaskArrays,
Evaluation.evalTimeSeries at eval/Evaluation.java:189-221).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-8


class LossFunction:
    """Enum-style constants matching the reference's LossFunctions.LossFunction."""

    MSE = "mse"
    L1 = "l1"
    XENT = "xent"  # binary cross entropy
    MCXENT = "mcxent"  # multi-class cross entropy
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    EXPLL = "expll"  # exponential log likelihood (poisson)
    RMSE_XENT = "rmse_xent"
    SQUARED_LOSS = "squared_loss"
    RECONSTRUCTION_CROSSENTROPY = "reconstruction_crossentropy"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    KL_DIVERGENCE = "kl_divergence"
    COSINE_PROXIMITY = "cosine_proximity"
    POISSON = "poisson"
    MEAN_ABSOLUTE_ERROR = "mae"


KNOWN_LOSSES = frozenset(
    v for k, v in vars(LossFunction).items() if not k.startswith("_")
)


def validate_loss(name) -> str:
    """Eagerly validate a loss name (init-time check; compute_loss only
    raises at trace time, too late for a good user error)."""
    if callable(name):
        return name
    low = str(name).lower()
    if low not in KNOWN_LOSSES:
        raise ValueError(
            f"Unknown loss function '{name}'. Known: {sorted(KNOWN_LOSSES)}")
    return low


def _align_mask(per, mask):
    """Broadcast a loss mask to the per-position loss array's shape
    (rank-pad trailing dims, then broadcast), in the loss dtype."""
    mask = jnp.broadcast_to(mask, per.shape) if mask.ndim == per.ndim else mask
    while mask.ndim < per.ndim:
        mask = mask[..., None]
    return jnp.broadcast_to(mask, per.shape).astype(per.dtype)


def _masked_mean(per_example, mask):
    """Mean over examples; if mask given, weight rows and renormalize."""
    if mask is None:
        return jnp.mean(per_example)
    m = _align_mask(per_example, mask)
    return jnp.sum(per_example * m) / jnp.maximum(jnp.sum(m), 1.0)


def _masked_per_example(per, mask):
    """Collapse per-position losses to one score PER EXAMPLE [B] (mask-
    weighted mean over any time/position dims) — the scoreExamples
    reduction (reference ScoreExamplesFunction, ScoreFlatMapFunction)."""
    if mask is None:
        if per.ndim <= 1:
            return per
        return jnp.mean(per.reshape(per.shape[0], -1), axis=-1)
    m = _align_mask(per, mask)
    num = jnp.sum((per * m).reshape(per.shape[0], -1), axis=-1)
    den = jnp.sum(m.reshape(per.shape[0], -1), axis=-1)
    return num / jnp.maximum(den, 1.0)


def _finish(per, mask, reduce):
    return _masked_mean(per, mask) if reduce else _masked_per_example(per, mask)


def compute_loss(name, labels, output, mask=None, *, logits=None,
                 reduce=True):
    """Compute a scalar loss (or per-example losses when ``reduce=False``).

    `output` is the activated output; for softmax/sigmoid output layers pass
    `logits` (the preactivation) as well so the fused stable path is used.

    A callable is the CUSTOM-loss path (reference LossFunction.CUSTOM):
    fn(labels, output) -> per-example loss, masked-meaned here.
    """
    if callable(name):
        return _finish(name(labels, output), mask, reduce)
    name = name.lower()
    if name in (LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD):
        if logits is not None:
            logp = jax.nn.log_softmax(logits, axis=-1)
        else:
            logp = jnp.log(jnp.clip(output, _EPS, 1.0))
        if (labels.ndim == logp.ndim - 1
                and jnp.issubdtype(labels.dtype, jnp.integer)):
            # sparse integer class labels [...,]: a gather instead of the
            # one-hot elementwise product — O(N) HBM traffic, not O(N*V).
            # NOTE: XLA clamps out-of-range indices, so labels must be in
            # [0, C); there is no -1 ignore-index convention — mask ignored
            # positions with labels_mask instead.
            per = -jnp.take_along_axis(logp, labels[..., None],
                                       axis=-1)[..., 0]
        else:
            per = -jnp.sum(labels * logp, axis=-1)
        return _finish(per, mask, reduce)
    if name == LossFunction.XENT:
        if logits is not None:
            # stable sigmoid BCE on logits
            per = jnp.sum(
                jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits))),
                axis=-1,
            )
        else:
            o = jnp.clip(output, _EPS, 1.0 - _EPS)
            per = -jnp.sum(labels * jnp.log(o) + (1 - labels) * jnp.log1p(-o), axis=-1)
        return _finish(per, mask, reduce)
    if name in (LossFunction.MSE, LossFunction.SQUARED_LOSS):
        per = jnp.sum((labels - output) ** 2, axis=-1)
        if name == LossFunction.MSE:
            per = per / output.shape[-1]
        return _finish(per, mask, reduce)
    if name in (LossFunction.L1, LossFunction.MEAN_ABSOLUTE_ERROR):
        per = jnp.sum(jnp.abs(labels - output), axis=-1)
        if name == LossFunction.MEAN_ABSOLUTE_ERROR:
            per = per / output.shape[-1]
        return _finish(per, mask, reduce)
    if name == LossFunction.RMSE_XENT:
        o = jnp.clip(output, _EPS, 1.0 - _EPS)
        xent = -(labels * jnp.log(o) + (1 - labels) * jnp.log1p(-o))
        per = jnp.sqrt(jnp.sum(xent**2, axis=-1) + _EPS)
        return _finish(per, mask, reduce)
    if name in (LossFunction.RECONSTRUCTION_CROSSENTROPY,):
        o = jnp.clip(output, _EPS, 1.0 - _EPS)
        per = -jnp.sum(labels * jnp.log(o) + (1 - labels) * jnp.log1p(-o), axis=-1)
        return _finish(per, mask, reduce)
    if name in (LossFunction.EXPLL, LossFunction.POISSON):
        o = jnp.clip(output, _EPS, None)
        per = jnp.sum(o - labels * jnp.log(o), axis=-1)
        return _finish(per, mask, reduce)
    if name == LossFunction.HINGE:
        per = jnp.sum(jnp.maximum(0.0, 1.0 - labels * output), axis=-1)
        return _finish(per, mask, reduce)
    if name == LossFunction.SQUARED_HINGE:
        per = jnp.sum(jnp.maximum(0.0, 1.0 - labels * output) ** 2, axis=-1)
        return _finish(per, mask, reduce)
    if name == LossFunction.KL_DIVERGENCE:
        o = jnp.clip(output, _EPS, 1.0)
        t = jnp.clip(labels, _EPS, 1.0)
        per = jnp.sum(t * (jnp.log(t) - jnp.log(o)), axis=-1)
        return _finish(per, mask, reduce)
    if name == LossFunction.COSINE_PROXIMITY:
        ln = labels / (jnp.linalg.norm(labels, axis=-1, keepdims=True) + _EPS)
        on = output / (jnp.linalg.norm(output, axis=-1, keepdims=True) + _EPS)
        per = -jnp.sum(ln * on, axis=-1)
        return _finish(per, mask, reduce)
    raise ValueError(f"Unknown loss function '{name}'")


def loss_fn(name):
    """Return a closure computing the named loss."""

    def fn(labels, output, mask=None, logits=None):
        return compute_loss(name, labels, output, mask, logits=logits)

    return fn
