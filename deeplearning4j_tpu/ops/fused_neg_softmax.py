"""Fused negative-sampling sampled-softmax scoring — one pass over the
gathered embedding rows.

The sharded embedding engine's SGNS step (embedding/engine.py) scores a
[B, D] center strip against its [B, D] positive rows and [B, K, D]
negative block: two sigmoid'd contractions whose results feed both the
loss and the closed-form gradients. This module fuses the two
contractions and the sigmoids into one Pallas program per row block —
the sampled-softmax inner loop of word2vec SGNS, following the
every-kernel-benchmarked discipline (Dragon-Alpha, arXiv:2305.08819):
registered in the ``neg_softmax`` autotune family, swept by
tools/kerneltune.py, resolved through the tuning table.

Dispatch follows the fused_sampling idiom: a shared math body
(`_score_body`) runs EXACTLY in both the kernel and the pure-jnp
reference, so off-TPU (interpret mode) and outside the `supports()`
envelope the results are bit-identical by construction. The reference
expressions are verbatim the legacy dense path's (nlp/lookup.sgns_step),
which is what makes the engine's ep=1 bit-parity contract hold on the
tiny-vocab shapes the envelope excludes.

The [B, K] negative-score output is padded to a [B, LANES] lane tile in
kernel (K is a handful; the last dimension must tile) and sliced back by
the public entry point.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deeplearning4j_tpu.ops import autotune


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def supports(batch: int, k: int, dim: int) -> bool:
    """Whether the Pallas kernel's envelope covers a (c [batch, dim],
    pos [batch, dim], neg [batch, k, dim]) triple: lane-tiled dim,
    sublane-tiled rows, K inside one lane tile (the padded neg-score
    block), and a legal (1, bn) positive-score row."""
    if dim % autotune.LANES != 0 or batch % 8 != 0:
        return False
    if not 0 < k <= autotune.LANES:
        return False
    bn = autotune.neg_softmax_rows(batch, dim)
    return bn % autotune.LANES == 0 or bn == batch


def _score_body(c, pos, neg):
    """The shared scoring math (kernel body AND jnp reference run
    exactly this — and it is verbatim nlp/lookup.sgns_step's forward):
    c/pos [bn, D], neg [bn, K, D]; returns sigmoid'd dot products
    (pos_score [bn], neg_score [bn, K])."""
    pos_score = jax.nn.sigmoid(jnp.einsum(
        "bd,bd->b", c, pos, preferred_element_type=jnp.float32))
    neg_score = jax.nn.sigmoid(jnp.einsum(
        "bd,bkd->bk", c, neg, preferred_element_type=jnp.float32))
    return pos_score, neg_score


def _neg_softmax_kernel(c_ref, pos_ref, neg_ref, pos_out_ref, neg_out_ref):
    pos_score, neg_score = _score_body(c_ref[...], pos_ref[...],
                                       neg_ref[...])
    pos_out_ref[...] = pos_score.reshape(pos_out_ref.shape)
    bn, k = neg_score.shape
    neg_out_ref[...] = jnp.pad(neg_score,
                               ((0, 0), (0, autotune.LANES - k)))


def _neg_softmax_pallas(c, pos, neg):
    B, D = c.shape
    K = neg.shape[1]
    bn = autotune.neg_softmax_rows(B, D)
    grid = (B // bn,)
    pos_score, neg_pad = pl.pallas_call(
        functools.partial(_neg_softmax_kernel),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((bn, D), lambda i: (i, 0)),
            pl.BlockSpec((bn, K, D), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bn), lambda i: (0, i)),
            pl.BlockSpec((bn, autotune.LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, B), c.dtype),
            jax.ShapeDtypeStruct((B, autotune.LANES), c.dtype),
        ],
        interpret=_use_interpret(),
    )(c, pos, neg)
    return pos_score[0], neg_pad[:, :K]


def neg_softmax_scores(c, pos, neg):
    """Sigmoid'd SGNS scores for a batch of (center, positive,
    K-negatives) triples: c/pos [B, D], neg [B, K, D] ->
    (pos_score [B], neg_score [B, K]).

    Inside the `supports()` envelope the fused Pallas kernel runs (row
    block from the ``neg_softmax`` autotune family; interpret mode
    off-TPU); outside it the SAME math runs as the pure-jnp reference —
    bit-identical to the legacy dense sgns_step forward."""
    B, D = c.shape
    K = neg.shape[1]
    if supports(B, K, D):
        return _neg_softmax_pallas(c, pos, neg)
    return _score_body(c, pos, neg)
