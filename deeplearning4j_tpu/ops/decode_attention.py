"""Attention against a paged KV cache — the decode-side op family.

The autoregressive serving path (serving/engine.py GenerationEngine)
threads a per-slot KV cache through a jitted step; its attention reads
are structurally different from training attention:

* `decode_attention` — SINGLE-query attention: one new token's query
  per cache row against everything written so far (`pos` keys). The
  [T, T] score matrix of the training kernels collapses to a [1, S]
  strip, so the cost driver is streaming the cache out of HBM, not the
  MXU — the knob is the key-block length `block_k` the cache is
  streamed in (page multiples), resolved through the ops/autotune.py
  tuning table under the `decode_attn` kernel family.
* `cache_attention` — the general (multi-query) form behind it, also
  the cross-chunk half of chunked prefill (nn/decode.py): chunk queries
  against the already-written cache prefix, returning (out, lse) so the
  caller can LSE-merge with the within-chunk flash result.

Implementation is a blocked lax.scan over key blocks with the standard
flash running-max/sum merge — an XLA-level kernel whose block_k is the
tuning knob (a hand-written Pallas single-query kernel would slot in
behind the same dispatch). Off-TPU the tuning table is inactive
(autotune.table_active), so interpret/CPU runs always use the
deterministic divisor-search default — bit-identical to the fallback by
construction. Scores accumulate in f32 regardless of cache dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import autotune

_NEG_INF = -1e30


@functools.partial(jax.jit, static_argnames=("block_k",))
def _cache_attention_blocked(q, k, v, key_limit, block_k):
    """q [B, H, Tq, D]; k, v [B, S, H, D] (cache layout: key position is
    the second axis so per-position scatter writes are contiguous);
    key_limit [B, Tq] — key j is visible to query (b, t) iff
    j < key_limit[b, t]. Returns (out [B, H, Tq, D] in q.dtype,
    lse [B, H, Tq] f32). All-masked rows produce a zero block and an
    lse at the mask floor, which a downstream lse merge weighs away."""
    B, S, H, D = k.shape
    Tq = q.shape[2]
    nb = S // block_k
    sm_scale = 1.0 / jnp.sqrt(jnp.float32(D))
    qf = q.astype(jnp.float32)
    # [B, S, H, D] -> [nb, B, H, bk, D] so scan carries one block per step
    kb = jnp.moveaxis(k.reshape(B, nb, block_k, H, D), 1, 0)
    kb = kb.transpose(0, 1, 3, 2, 4)
    vb = jnp.moveaxis(v.reshape(B, nb, block_k, H, D), 1, 0)
    vb = vb.transpose(0, 1, 3, 2, 4)

    m0 = jnp.full((B, H, Tq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    acc0 = jnp.zeros((B, H, Tq, D), jnp.float32)

    def body(carry, blk):
        m, l, acc, j0 = carry
        k_j, v_j = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_j.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * sm_scale
        idx = j0 + jnp.arange(block_k)
        visible = idx[None, None, None, :] < key_limit[:, None, :, None]
        s = jnp.where(visible, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + p.sum(-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_j.astype(jnp.float32))
        return (m_new, l_new, acc_new, j0 + block_k), None

    (m, l, acc, _), _ = jax.lax.scan(
        body, (m0, l0, acc0, jnp.int32(0)), (kb, vb))
    out = jnp.where(l[..., None] > 0.0, acc / jnp.maximum(l, 1e-30)[..., None],
                    0.0)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out.astype(q.dtype), lse


def cache_attention(q, k, v, key_limit):
    """Multi-query attention over a KV cache with a per-query visible-key
    bound. Shapes as `_cache_attention_blocked`; block_k resolves through
    the `decode_attn` tuning-table family (off-TPU: the deterministic
    divisor-search default — bit-identical fallback)."""
    S, D = k.shape[1], k.shape[3]
    bk = autotune.decode_block(S, D)
    return _cache_attention_blocked(q, k, v, key_limit, bk)


def decode_attention(q, k, v, pos):
    """Single-query decode attention: q [B, H, D] is the new token's
    query at position pos [B] per cache row; the token's own K/V must
    already be written at `pos`, so keys j <= pos are visible. Returns
    [B, H, D] in q.dtype."""
    out, _ = cache_attention(q[:, :, None, :], k, v,
                             (pos + 1)[:, None])
    return out[:, :, 0, :]
