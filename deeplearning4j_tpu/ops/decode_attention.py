"""Attention against a paged KV cache — the decode-side op family.

The autoregressive serving path (serving/engine.py GenerationEngine)
threads a per-slot KV cache through a jitted step; its attention reads
are structurally different from training attention:

* `decode_attention` — SINGLE-query attention: one new token's query
  per cache row against everything written so far (`pos` keys). The
  [T, T] score matrix of the training kernels collapses to a [1, S]
  strip, so the cost driver is streaming the cache out of HBM, not the
  MXU — the knob is the key-block length `block_k` the cache is
  streamed in (page multiples), resolved through the ops/autotune.py
  tuning table under the `decode_attn` kernel family.
* `cache_attention` — the general (multi-query) form behind it, also
  the cross-chunk half of chunked prefill (nn/decode.py): chunk queries
  against the already-written cache prefix, returning (out, lse) so the
  caller can LSE-merge with the within-chunk flash result.

Implementation is a blocked lax.scan over key blocks with the standard
flash running-max/sum merge — an XLA-level kernel whose block_k is the
tuning knob (a hand-written Pallas single-query kernel would slot in
behind the same dispatch). Off-TPU the tuning table is inactive
(autotune.table_active), so interpret/CPU runs always use the
deterministic divisor-search default — bit-identical to the fallback by
construction. Scores accumulate in f32 regardless of cache dtype.

INT8 QUANTIZED CACHE (r16): the `*_q8` twins read a cache stored as
int8 codes plus one f32 scale per (row, page, head) — per-page
symmetric quantization, scale = maxabs/127, so a page of K (or V)
costs page_size*D bytes instead of page_size*D*4 and HBM streaming
shrinks ~4x (slots per HBM byte is the serving headline this feeds).
Dequantization happens INSIDE the blocked scan body — a code block
[bk, D] times its page scales, straight into the f32 score dot — so
the quantized path streams codes, never a materialized f32 cache. The
`decode_attn_q8` tuning family constrains block_k to page multiples
(a block may not split a page's scale broadcast). Cache WRITES go
through `quantized_cache_update`: gather the page-aligned window
covering the new positions, dequantize, insert, zero positions past
the write head (stale values from a previous slot tenancy must not
inflate the fresh page's maxabs), recompute page scales, requantize,
scatter codes + scales back. Re-rounding a page whose scale did not
change is EXACT (round(code*s/s) == code), so settled pages do not
drift as their neighbors fill in.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import autotune

_NEG_INF = -1e30


@functools.partial(jax.jit, static_argnames=("block_k",))
def _cache_attention_blocked(q, k, v, key_limit, block_k):
    """q [B, H, Tq, D]; k, v [B, S, H, D] (cache layout: key position is
    the second axis so per-position scatter writes are contiguous);
    key_limit [B, Tq] — key j is visible to query (b, t) iff
    j < key_limit[b, t]. Returns (out [B, H, Tq, D] in q.dtype,
    lse [B, H, Tq] f32). All-masked rows produce a zero block and an
    lse at the mask floor, which a downstream lse merge weighs away."""
    B, S, H, D = k.shape
    Tq = q.shape[2]
    nb = S // block_k
    sm_scale = 1.0 / jnp.sqrt(jnp.float32(D))
    qf = q.astype(jnp.float32)
    # [B, S, H, D] -> [nb, B, H, bk, D] so scan carries one block per step
    kb = jnp.moveaxis(k.reshape(B, nb, block_k, H, D), 1, 0)
    kb = kb.transpose(0, 1, 3, 2, 4)
    vb = jnp.moveaxis(v.reshape(B, nb, block_k, H, D), 1, 0)
    vb = vb.transpose(0, 1, 3, 2, 4)

    m0 = jnp.full((B, H, Tq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    acc0 = jnp.zeros((B, H, Tq, D), jnp.float32)

    def body(carry, blk):
        m, l, acc, j0 = carry
        k_j, v_j = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_j.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * sm_scale
        idx = j0 + jnp.arange(block_k)
        visible = idx[None, None, None, :] < key_limit[:, None, :, None]
        s = jnp.where(visible, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + p.sum(-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_j.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return (m_new, l_new, acc_new, j0 + block_k), None

    (m, l, acc, _), _ = jax.lax.scan(
        body, (m0, l0, acc0, jnp.int32(0)), (kb, vb))
    out = jnp.where(l[..., None] > 0.0, acc / jnp.maximum(l, 1e-30)[..., None],
                    0.0)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out.astype(q.dtype), lse


def cache_attention(q, k, v, key_limit):
    """Multi-query attention over a KV cache with a per-query visible-key
    bound. Shapes as `_cache_attention_blocked`; block_k resolves through
    the `decode_attn` tuning-table family (off-TPU: the deterministic
    divisor-search default — bit-identical fallback)."""
    S, D = k.shape[1], k.shape[3]
    bk = autotune.decode_block(S, D)
    return _cache_attention_blocked(q, k, v, key_limit, bk)


def decode_attention(q, k, v, pos):
    """Single-query decode attention: q [B, H, D] is the new token's
    query at position pos [B] per cache row; the token's own K/V must
    already be written at `pos`, so keys j <= pos are visible. Returns
    [B, H, D] in q.dtype."""
    out, _ = cache_attention(q[:, :, None, :], k, v,
                             (pos + 1)[:, None])
    return out[:, :, 0, :]


# ----------------------------------------------------- int8 paged cache

def quantize_pages(x, page_size: int):
    """Per-page symmetric int8 quantization of a cache tensor
    x [B, S, H, D] (S a page multiple). Returns (codes int8 [B, S, H, D],
    scales f32 [B, S//page_size, H]) with scale = maxabs/127 per
    (row, page, head). Round-trip error is bounded by scale/2 per
    element — the bound tests/test_speculative.py proves."""
    B, S, H, D = x.shape
    n_pages = S // page_size
    xp = x.astype(jnp.float32).reshape(B, n_pages, page_size, H, D)
    amax = jnp.max(jnp.abs(xp), axis=(2, 4))
    scales = jnp.maximum(amax, 1e-8) / 127.0
    codes = jnp.clip(jnp.round(xp / scales[:, :, None, :, None]),
                     -127, 127).astype(jnp.int8)
    return codes.reshape(B, S, H, D), scales


def dequantize_pages(codes, scales, page_size: int):
    """Inverse of `quantize_pages` (up to the rounding error):
    codes int8 [B, S, H, D] * per-page scales [B, S//ps, H] -> f32."""
    B, S, H, D = codes.shape
    n_pages = S // page_size
    cp = codes.astype(jnp.float32).reshape(B, n_pages, page_size, H, D)
    return (cp * scales[:, :, None, :, None]).reshape(B, S, H, D)


def quantized_cache_update(codes, scales, new_vals, rows, positions,
                           page_size: int):
    """Write new K (or V) values into an int8 paged cache.

    codes [B, S, H, D] int8, scales [B, S//ps, H] f32; new_vals
    [b, T, H, D]; rows [b] (distinct cache rows); positions [b, T]
    (contiguous per row — a prefill chunk or a verify window).
    Out-of-range positions (the engine's inactive-row scratch, or a
    speculative tail past capacity) are DROPPED, matching the f32
    cache's reliance on jax scatter's drop-out-of-bounds default.

    The page containing a new position must be requantized (its maxabs
    may change), so the update works on the page-aligned window that
    covers the write: gather -> dequantize -> insert -> zero past the
    write head (stale values from a prior tenancy of the row must not
    set the fresh scale) -> new per-page scales -> requantize ->
    scatter. Returns (codes, scales)."""
    B, S, H, D = codes.shape
    b, T = positions.shape
    ps = page_size
    W = min(((T + ps - 1) // ps + 1) * ps, S)
    nw = W // ps
    pos_min = jnp.min(positions, axis=1)
    w0 = jnp.clip(pos_min // ps * ps, 0, S - W)
    widx = w0[:, None] + jnp.arange(W)                     # [b, W]
    p0 = w0 // ps
    pidx = p0[:, None] + jnp.arange(nw)                    # [b, nw]
    wcodes = codes[rows[:, None], widx]                    # [b, W, H, D]
    wscales = scales[rows[:, None], pidx]                  # [b, nw, H]
    wvals = (wcodes.astype(jnp.float32)
             * jnp.repeat(wscales, ps, axis=1)[:, :, :, None])
    local = positions - w0[:, None]
    valid = (positions < S) & (local >= 0) & (local < W)
    # invalid entries scatter to index W — out of bounds, dropped
    local_s = jnp.where(valid, local, W)
    wvals = wvals.at[jnp.arange(b)[:, None], local_s].set(
        new_vals.astype(jnp.float32))
    # zero everything past this row's write head: those positions are
    # invisible until overwritten (key_limit), and stale garbage there
    # would otherwise inflate the page maxabs and crush fresh precision
    pos_max = jnp.max(jnp.where(valid, positions, -1), axis=1)
    wvals = jnp.where((widx > pos_max[:, None])[:, :, None, None],
                      0.0, wvals)
    wq = wvals.reshape(b, nw, ps, H, D)
    amax = jnp.max(jnp.abs(wq), axis=(2, 4))
    new_scales = jnp.maximum(amax, 1e-8) / 127.0
    qcodes = jnp.clip(jnp.round(wq / new_scales[:, :, None, :, None]),
                      -127, 127).astype(jnp.int8).reshape(b, W, H, D)
    codes = codes.at[rows[:, None], widx].set(qcodes)
    scales = scales.at[rows[:, None], pidx].set(new_scales)
    return codes, scales


@functools.partial(jax.jit, static_argnames=("block_k", "page_size"))
def _cache_attention_blocked_q8(q, k_codes, v_codes, k_scale, v_scale,
                                key_limit, block_k, page_size):
    """The int8 twin of `_cache_attention_blocked`: identical scan and
    running-max merge, but each key block arrives as int8 codes and is
    dequantized in the body (code * per-page scale, f32) right before
    the score dot. block_k is a page multiple so the [B, ppb, H] scale
    slice broadcasts across whole pages."""
    B, S, H, D = k_codes.shape
    Tq = q.shape[2]
    nb = S // block_k
    ppb = block_k // page_size
    sm_scale = 1.0 / jnp.sqrt(jnp.float32(D))
    qf = q.astype(jnp.float32)
    kb = jnp.moveaxis(k_codes.reshape(B, nb, block_k, H, D), 1, 0)
    kb = kb.transpose(0, 1, 3, 2, 4)
    vb = jnp.moveaxis(v_codes.reshape(B, nb, block_k, H, D), 1, 0)
    vb = vb.transpose(0, 1, 3, 2, 4)
    ksb = jnp.moveaxis(k_scale.reshape(B, nb, ppb, H), 1, 0)  # [nb,B,ppb,H]
    vsb = jnp.moveaxis(v_scale.reshape(B, nb, ppb, H), 1, 0)

    m0 = jnp.full((B, H, Tq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    acc0 = jnp.zeros((B, H, Tq, D), jnp.float32)

    def body(carry, blk):
        m, l, acc, j0 = carry
        k_j, v_j, ks_j, vs_j = blk
        # [B, ppb, H] -> [B, H, bk, 1]: one scale per page, per head
        ks = jnp.repeat(ks_j, page_size, axis=1).transpose(0, 2, 1)
        vs = jnp.repeat(vs_j, page_size, axis=1).transpose(0, 2, 1)
        kf = k_j.astype(jnp.float32) * ks[..., None]
        vf = v_j.astype(jnp.float32) * vs[..., None]
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf,
                       preferred_element_type=jnp.float32) * sm_scale
        idx = j0 + jnp.arange(block_k)
        visible = idx[None, None, None, :] < key_limit[:, None, :, None]
        s = jnp.where(visible, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + p.sum(-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vf,
            preferred_element_type=jnp.float32)
        return (m_new, l_new, acc_new, j0 + block_k), None

    (m, l, acc, _), _ = jax.lax.scan(
        body, (m0, l0, acc0, jnp.int32(0)), (kb, vb, ksb, vsb))
    out = jnp.where(l[..., None] > 0.0,
                    acc / jnp.maximum(l, 1e-30)[..., None], 0.0)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out.astype(q.dtype), lse


def cache_attention_q8(q, k_codes, v_codes, k_scale, v_scale, key_limit,
                       page_size: int):
    """Multi-query attention over an int8 paged KV cache. Shapes as
    `_cache_attention_blocked_q8`; block_k resolves through the
    `decode_attn_q8` tuning family (page-multiple candidates; off-TPU
    the deterministic page-multiple divisor default)."""
    S, D = k_codes.shape[1], k_codes.shape[3]
    bk = autotune.decode_block_q8(S, D, page_size)
    return _cache_attention_blocked_q8(q, k_codes, v_codes, k_scale,
                                       v_scale, key_limit, bk, page_size)
