"""Op surface — the tensor substrate audit (SURVEY.md §7 step 1).

The reference delegates all math to the external ND4J `INDArray` API
(gemm, BLAS level-1, named transforms, im2col/col2im convolution, RNG —
see reference deeplearning4j-core/pom.xml:53-59 and SURVEY.md §2.1).
Here `jax.numpy`/`jax.lax` IS the array substrate: ops lower straight to
XLA:TPU. This package pins the op surface the framework relies on:

- activations:  named activation registry ("relu", "tanh", ... — the
  reference resolves transforms by string name through its op executioner)
- losses:      LossFunctions equivalents (reference ND4J LossFunctions)
- conv:        lax.conv_general_dilated / reduce_window replace
               Convolution.im2col/col2im (reference ConvolutionLayer.java:125,151)
"""

from deeplearning4j_tpu.ops.activations import Activations, get_activation
from deeplearning4j_tpu.ops.losses import LossFunction, compute_loss, loss_fn

__all__ = [
    "Activations",
    "get_activation",
    "LossFunction",
    "compute_loss",
    "loss_fn",
]
