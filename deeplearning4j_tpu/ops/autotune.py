"""Kernel autotuning layer — block sizes as data, not literals.

Every Pallas kernel in ops/ sizes its grid through this module. Until r8
the knobs were frozen module constants ("swept on v5e once",
BLOCK_Q_MAX = 512 et al.); the ROADMAP "push MFU" item calls for
perf-library discipline per Dragon-Alpha (arXiv 2305.08819): every
kernel variant benchmarked, budgeted, and regression-gated. This module
is the knob half of that loop — `tools/kerneltune.py` is the bench half.

Resolution order for a kernel's block parameters:

1. an active `override(...)` context (tests and the kerneltune sweep
   force candidate variants through the real dispatch);
2. a checked-in tuning-table entry
   (`deeplearning4j_tpu/ops/tuning_table.json`) keyed on
   ``(kernel, T, D, causal, dropout, masked)`` — applied ON TPU only
   (or under ``DL4J_TPU_TUNING=force``), so off-TPU/interpret runs are
   bit-identical to the deterministic fallback;
3. the deterministic heuristics (the pre-r8 constants, now living
   here) — any table miss degrades to exactly the old behavior.

Table schema (version 1)::

    {"version": 1,
     "provenance": {"device_kind": ..., "backend": ..., "date": ...,
                    "tool": "tools/kerneltune.py", ...},
     "entries": {
       "flash_fwd|T512|D64|c1|d0|m0": {
           "block_q": 512, "block_k": 512, "g": 8,
           "best_us": 129.0, "default_us": 263.0},
       ...}}

Entry params are kernel-specific: flash_fwd/flash_bwd take
``block_q``/``block_k``/``g``; flash_fwd_qkv(+_pair)/flash_bwd_qkv
(+_pair) take ``g``; flash_chunk takes ``chunk``; fused_layer_norm takes
``rows``; softmax_xent takes ``block_n``/``block_v`` (caps — the row
count varies per call while the key is (V, d), so the caps feed the same
divisor search the defaults do); decode_attn takes ``block_k`` (the key
block a decode step streams the paged KV cache in — page multiples
dividing the cache capacity S, keyed on (S, head_dim));
decode_attn_q8 takes ``block_k`` too, further constrained to page-size
multiples (the int8 cache's scale grid is per page, so a key block must
cover whole pages); sample takes ``rows`` (the fused sampling kernel's
row block over the [B, V] logits, keyed on (B, V) with the
fused_layer_norm stat-row legality rule); neg_softmax takes ``rows``
(the fused negative-sampling sampled-softmax kernel's row block over
the [B, D] center/context strips, keyed on (B, D) with the same
stat-row rule for its [1, B] positive-score row). Every resolved
value is validated
against the kernel's structural constraints (divisibility, lane tiling,
unroll budget) before use; an invalid entry falls back to the
heuristics rather than producing an uncompilable grid.

Timings in entries are provenance, not configuration — the resolution
functions read only the param fields.

graftlint G016 enforces the inverse contract: Pallas block-size/grid
literals hardcoded outside this module are findings.

Pure stdlib at module level (the tools/ stub-import idiom); jax is
imported lazily inside `table_active` only.
"""

from __future__ import annotations

import json
import os
import threading

# Hardware tile constants (structural, not tunable): the MXU is 128x128,
# the VPU lane width is 128 — every block's minor dim is a multiple of
# LANES and sequence blocks are multiples of BLOCK.
LANES = 128
BLOCK = 128

# ---------------------------------------------------------------- defaults
#
# The deterministic heuristics — the pre-r8 frozen knobs, each with its
# original measurement note. These are the fallback for every table miss
# and the ONLY resolution used off-TPU (bit-identical interpret runs).

# Flash-attention block caps (swept on v5e, r2): larger q/k blocks
# amortize the per-program fixed cost and feed the MXU bigger dots; the
# caps keep scores [bq, bk] f32 and the full-T K/V copies inside VMEM.
DEFAULT_BLOCK_Q_MAX = 512
DEFAULT_BLOCK_K_MAX = 512

# Fused softmax-xent blocks (swept on v5e at N=16384, d=256, V=10240,
# r2+r5): 1024-row blocks x 2048-wide vocab chunks under the 32MB scoped
# limit; wider chunks and smaller row blocks both LOSE.
DEFAULT_XENT_BLOCK_N = 1024
DEFAULT_XENT_BLOCK_V = 2048

# Fused layer-norm row block (r3).
DEFAULT_LN_ROW_BLOCK = 512

# Fused sampling row block (r16): each program reduces a [rows, V]
# logits block to `rows` token ids, so the row block trades program
# count against the f32 score strip's VMEM footprint at wide vocabs.
DEFAULT_SAMPLE_ROW_BLOCK = 256

# Fused negative-sampling sampled-softmax row block (r19): each program
# scores a [rows, D] center strip against its positive row and [rows, K,
# D] negative block, so the row block trades program count against the
# [rows, K, D] negative block's VMEM footprint.
DEFAULT_NEG_SOFTMAX_ROW_BLOCK = 128

# Decode-attention key block (r11): single-query attention against a
# paged KV cache streams the cache in blocks of block_k key positions
# (page multiples) with a running-max/lse merge. The default cap keeps
# a block's [bk, D] K/V slice plus the f32 score strip well inside
# VMEM at every served head dim; the cap feeds a divisor search over
# the cache capacity S (which is page-quantized, so divisors exist).
DEFAULT_DECODE_BLOCK_K = 512

# Kernel-proven chunk-tile lengths for the long-context loop, largest
# first (the single home for the tiling envelope quoted in error
# messages). 8192 is the monolithic kernels' VMEM envelope at
# head_dim <= 128 (0.69 MFU in-model; 15360+ busts VMEM with
# 512-blocks) — the D-aware bound below shrinks the cap as D grows.
CHUNK_TILES = (8192, 4096, 2048, 1024, 512)

# The backward's VMEM working set streams full-tile [T, D] K/V (resp.
# Q/dO) pairs, so the proven tile LENGTH scales inversely with head
# dim: tile * max(D, 128) <= TILE_ELEM_BUDGET keeps the working set at
# or below the measured D=128 envelope (8192 * 128). D=256 caps tiles
# at 4096, D=512 at 2048 — the "D-aware tile bound" tier (ADVICE r5 #2:
# D > 128 long-T previously had no supported path at all).
TILE_ELEM_BUDGET = CHUNK_TILES[0] * 128

ENV_TUNING = "DL4J_TPU_TUNING"  # "force" | "off" | unset (TPU-only)

SCHEMA_VERSION = 1

TABLE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tuning_table.json")

# Params each kernel family may tune; validation rejects anything else.
KERNEL_PARAMS = {
    "flash_fwd": ("block_q", "block_k", "g"),
    "flash_bwd": ("block_q", "block_k", "g"),
    "flash_fwd_qkv": ("g",),
    "flash_bwd_qkv": ("g",),
    "flash_fwd_qkv_pair": ("g",),
    "flash_bwd_qkv_pair": ("g",),
    "flash_chunk": ("chunk",),
    "fused_layer_norm": ("rows",),
    "softmax_xent": ("block_n", "block_v"),
    "decode_attn": ("block_k",),
    "decode_attn_q8": ("block_k",),
    "sample": ("rows",),
    "neg_softmax": ("rows",),
}

# Timing/provenance fields an entry may carry alongside its params.
ENTRY_META_FIELDS = ("best_us", "default_us", "candidates", "source")


def pick_block(n: int, cap: int, base: int = BLOCK) -> int:
    """Largest power-of-two divisor of n up to cap (n % base == 0
    assumed). The shared divisor search of the flash and fused-head
    kernels — and the validator tuned caps feed."""
    b = base
    while b * 2 <= cap and n % (b * 2) == 0:
        b *= 2
    return min(b, n)


def config_key(kernel: str, T: int, D: int, *, causal: bool = False,
               dropout: bool = False, masked: bool = False) -> str:
    """The table key: kernel|T|D|causal|dropout|masked. T and D are the
    kernel's own dims (flash: sequence x head_dim; fused_layer_norm:
    rows x feature dim; softmax_xent: vocab x feature dim)."""
    return (f"{kernel}|T{int(T)}|D{int(D)}|c{int(bool(causal))}"
            f"|d{int(bool(dropout))}|m{int(bool(masked))}")


def parse_key(key: str) -> dict:
    """Inverse of config_key — used by kerneltune/benchdiff to name
    entries. Raises ValueError on a malformed key."""
    parts = key.split("|")
    if len(parts) != 6:
        raise ValueError(f"malformed tuning key {key!r}")
    kernel, t, d, c, dr, m = parts
    if not (t[:1] == "T" and d[:1] == "D" and c[:1] == "c"
            and dr[:1] == "d" and m[:1] == "m"):
        raise ValueError(f"malformed tuning key {key!r}")
    return {"kernel": kernel, "T": int(t[1:]), "D": int(d[1:]),
            "causal": bool(int(c[1:])), "dropout": bool(int(dr[1:])),
            "masked": bool(int(m[1:]))}


def validate_table(table) -> list[str]:
    """Schema check -> list of problems (empty = valid). Used by the
    loader (a broken checked-in table must fail loudly at load, not as
    a Mosaic error mid-compile), kerneltune before writing, and the
    round-trip tests."""
    problems = []
    if not isinstance(table, dict):
        return ["table is not a JSON object"]
    if table.get("version") != SCHEMA_VERSION:
        problems.append(f"version {table.get('version')!r} != "
                        f"{SCHEMA_VERSION}")
    entries = table.get("entries")
    if not isinstance(entries, dict):
        return problems + ["missing 'entries' object"]
    for key, entry in entries.items():
        try:
            cfg = parse_key(key)
        except ValueError as exc:
            problems.append(str(exc))
            continue
        allowed = KERNEL_PARAMS.get(cfg["kernel"])
        if allowed is None:
            problems.append(f"{key}: unknown kernel {cfg['kernel']!r}")
            continue
        if not isinstance(entry, dict):
            problems.append(f"{key}: entry is not an object")
            continue
        for field, value in entry.items():
            if field in ENTRY_META_FIELDS:
                continue
            if field not in allowed:
                problems.append(f"{key}: param {field!r} not tunable "
                                f"for {cfg['kernel']} (allowed: "
                                f"{list(allowed)})")
            elif not isinstance(value, int) or value < 1:
                problems.append(f"{key}: param {field!r} must be a "
                                f"positive int, got {value!r}")
    return problems


# ------------------------------------------------------------ table state

_lock = threading.Lock()
_cache: dict = {"path": None, "table": None}
_overrides: list[dict] = []  # innermost last; each {key -> params}


def load_table(path: str | None = None) -> dict:
    """Load (and cache) the tuning table. A missing file is an empty
    table (every lookup falls back); a malformed file raises at load."""
    path = path or TABLE_PATH
    with _lock:
        if _cache["path"] == path and _cache["table"] is not None:
            return _cache["table"]
        if not os.path.exists(path):
            table = {"version": SCHEMA_VERSION, "provenance": {},
                     "entries": {}}
        else:
            with open(path) as fh:
                table = json.load(fh)
            problems = validate_table(table)
            if problems:
                raise ValueError(
                    f"invalid tuning table {path}: " + "; ".join(problems))
        _cache["path"] = path
        _cache["table"] = table
        return table


def reload_table(path: str | None = None) -> dict:
    """Drop the cache and re-read (kerneltune just rewrote the file)."""
    with _lock:
        _cache["path"] = None
        _cache["table"] = None
    return load_table(path)


def table_active() -> bool:
    """Whether table entries apply. Off-TPU the answer is no (interpret
    runs stay bit-identical to the deterministic fallback — the tier-1
    contract); DL4J_TPU_TUNING=force/off overrides for tests and
    debugging."""
    env = os.environ.get(ENV_TUNING, "").lower()
    if env in ("force", "1", "on"):
        return True
    if env in ("off", "0"):
        return False
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:  # jax absent (tools stub imports): fallback only
        return False


class override:
    """Context manager forcing explicit params for a kernel config —
    the hook kerneltune times candidates through and the parity tests
    pin variants with. Matches by exact config key, or by bare kernel
    name for every config of that kernel::

        with autotune.override({"flash_fwd": {"block_q": 256}}):
            flash_attention(q, k, v, causal=True)
    """

    def __init__(self, mapping: dict):
        self.mapping = dict(mapping)

    def __enter__(self):
        _overrides.append(self.mapping)
        return self

    def __exit__(self, *exc):
        _overrides.remove(self.mapping)
        return False


def lookup(kernel: str, T: int, D: int, *, causal: bool = False,
           dropout: bool = False, masked: bool = False) -> dict | None:
    """The raw entry for a config (override > table > None). Callers go
    through the typed resolution functions below, which validate."""
    key = config_key(kernel, T, D, causal=causal, dropout=dropout,
                     masked=masked)
    for mapping in reversed(_overrides):
        if key in mapping:
            return mapping[key]
        if kernel in mapping:
            return mapping[kernel]
    if not table_active():
        return None
    return load_table()["entries"].get(key)


# ------------------------------------------------------------- resolution

def _valid_block(b, T) -> bool:
    return (isinstance(b, int) and b >= BLOCK and b % BLOCK == 0
            and T % b == 0)


def flash_blocks(T: int, D: int, *, causal: bool, dropout: bool,
                 masked: bool, kernel: str = "flash_fwd") -> tuple[int, int]:
    """(block_q, block_k) for the monolithic flash kernels. Tuned values
    must be lane-tile multiples dividing T; anything else falls back to
    the swept 512-caps divisor search."""
    e = lookup(kernel, T, D, causal=causal, dropout=dropout, masked=masked)
    if e:
        bq, bk = e.get("block_q"), e.get("block_k")
        if _valid_block(bq, T) and _valid_block(bk, T):
            return bq, bk
    return (pick_block(T, DEFAULT_BLOCK_Q_MAX),
            pick_block(T, DEFAULT_BLOCK_K_MAX))


def flash_g(kernel: str, BH: int, T: int, D: int, *, causal: bool,
            dropout: bool, masked: bool) -> int | None:
    """Tuned per-program G-batching for a flash kernel, or None (caller
    falls back to the VMEM-budget heuristic). A tuned G must divide the
    batch*head count it is applied to."""
    e = lookup(kernel, T, D, causal=causal, dropout=dropout, masked=masked)
    if e:
        g = e.get("g")
        if isinstance(g, int) and g >= 1 and BH % g == 0:
            return g
    return None


def max_tile_for_dim(D: int | None) -> int:
    """Largest kernel-proven chunk tile for a head dim: the D-aware
    bound (tile * max(D, 128) <= TILE_ELEM_BUDGET). None means the
    caller has no head-dim information — treated as the D <= 128
    envelope (the pre-r8 behavior)."""
    if not D or D <= LANES:
        return CHUNK_TILES[0]
    for c in CHUNK_TILES:
        if c * D <= TILE_ELEM_BUDGET:
            return c
    return 0


def chunk_tile(T: int, D: int | None, *, causal: bool, dropout: bool,
               masked: bool, fits) -> int | None:
    """Tuned chunk length for the long-context loop, or None. `fits` is
    the caller's structural predicate (divisibility + unroll budget) so
    the validation rule lives with the loop, not here."""
    e = lookup("flash_chunk", T, D or 0, causal=causal, dropout=dropout,
               masked=masked)
    if e:
        c = e.get("chunk")
        if (isinstance(c, int) and c in CHUNK_TILES
                and c <= max_tile_for_dim(D) and fits(c)):
            return c
    return None


def decode_block(S: int, D: int) -> int:
    """Key-block length for single-query decode attention against a
    cache of capacity S (ops/decode_attention.py). The tuned value must
    divide S (the cache capacity is page-quantized, so page-multiple
    candidates always divide); any miss falls back to the largest
    divisor of S within the swept cap — deterministic, so off-TPU runs
    (table inactive) are bit-identical to the fallback."""
    e = lookup("decode_attn", S, D)
    if e:
        bk = e.get("block_k")
        if isinstance(bk, int) and 1 <= bk <= S and S % bk == 0:
            return bk
    if S <= DEFAULT_DECODE_BLOCK_K:
        return S
    for bk in range(DEFAULT_DECODE_BLOCK_K, 0, -1):
        if S % bk == 0:
            return bk
    return S  # unreachable: 1 divides S


def decode_block_q8(S: int, D: int, page_size: int) -> int:
    """Key-block length for the int8 quantized decode-attention variant
    (ops/decode_attention.py). Same contract as `decode_block` with one
    extra structural rule: the block must be a multiple of the cache
    page size, because dequantization broadcasts one per-page scale
    across each page inside a block — a block may not split a page.
    The cache capacity S is page-quantized, so page-multiple divisors
    always exist; the fallback takes the largest one within the swept
    cap (deterministic, bit-identical off-TPU)."""
    ps = max(1, int(page_size))
    e = lookup("decode_attn_q8", S, D)
    if e:
        bk = e.get("block_k")
        if (isinstance(bk, int) and 1 <= bk <= S and S % bk == 0
                and bk % ps == 0):
            return bk
    if S <= DEFAULT_DECODE_BLOCK_K:
        return S
    cap = DEFAULT_DECODE_BLOCK_K // ps * ps
    for bk in range(max(cap, ps), 0, -ps):
        if S % bk == 0:
            return bk
    return S  # unreachable: S is a page multiple, so ps divides S


def sample_rows(B: int, V: int) -> int:
    """Row block for the fused sampling kernel (ops/fused_sampling.py).
    The [1, B] token row uses (1, bn) blocks, legal only when bn is a
    lane-tile multiple or the whole batch — the fused_layer_norm
    stat-row rule, enforced for tuned values too."""
    e = lookup("sample", B, V)
    if e:
        bn = e.get("rows")
        if (isinstance(bn, int) and bn >= 8 and B % bn == 0
                and (bn % LANES == 0 or bn == B)):
            return bn
    b = 8
    while b * 2 <= DEFAULT_SAMPLE_ROW_BLOCK and B % (b * 2) == 0:
        b *= 2
    return b


def neg_softmax_rows(B: int, D: int) -> int:
    """Row block for the fused negative-sampling sampled-softmax kernel
    (ops/fused_neg_softmax.py). Its [1, B] positive-score row uses
    (1, bn) blocks, so the same stat-row legality rule as `sample_rows`
    applies: bn a lane-tile multiple or the whole batch."""
    e = lookup("neg_softmax", B, D)
    if e:
        bn = e.get("rows")
        if (isinstance(bn, int) and bn >= 8 and B % bn == 0
                and (bn % LANES == 0 or bn == B)):
            return bn
    b = 8
    while b * 2 <= DEFAULT_NEG_SOFTMAX_ROW_BLOCK and B % (b * 2) == 0:
        b *= 2
    return b


def ln_rows(N: int, C: int) -> int:
    """Row block for fused_layer_norm. The [1, N] stat rows use (1, bn)
    blocks, legal only when bn is a lane-tile multiple or the whole row
    dim — the same rule supports() gates on, enforced here for tuned
    values too."""
    e = lookup("fused_layer_norm", N, C)
    if e:
        bn = e.get("rows")
        if (isinstance(bn, int) and bn >= 8 and N % bn == 0
                and (bn % LANES == 0 or bn == N)):
            return bn
    b = 8
    while b * 2 <= DEFAULT_LN_ROW_BLOCK and N % (b * 2) == 0:
        b *= 2
    return b


def xent_blocks(N: int, d: int, V: int) -> tuple[int, int]:
    """(block_n, block_v) for the fused softmax-xent head. Tuned values
    are CAPS (the key is (V, d) while N varies per call): block_n feeds
    the same divisor search as the default, block_v is floored to a
    lane multiple and capped at the vocab."""
    e = lookup("softmax_xent", V, d)
    bn_cap, bv_cap = DEFAULT_XENT_BLOCK_N, DEFAULT_XENT_BLOCK_V
    if e:
        tbn, tbv = e.get("block_n"), e.get("block_v")
        if isinstance(tbn, int) and tbn >= BLOCK and tbn % BLOCK == 0:
            bn_cap = tbn
        if isinstance(tbv, int) and tbv >= LANES and tbv % LANES == 0:
            bv_cap = tbv
    bn = pick_block(N, bn_cap)
    # VMEM working set scales with d*bv: shrink the chunk as the feature
    # dim grows (the swept envelope is bn=1024 x bv=2048 at d=256);
    # floor at 512 lanes, cap at the swept width and the vocab itself
    bv = max(512, min(bv_cap, (bv_cap * 256 // d) // LANES * LANES))
    return bn, min(V, bv)
