"""Fused on-device sampling — temperature/top-k/top-p in one pass.

The serving decode loop's sampling used to be the classic host round
trip G019/G024 police: pull the whole [B, V] logits row home, softmax
and argsort in numpy, `np.random.choice` per slot — one device->host
transfer plus host-side O(V log V) work per emitted token. This module
keeps the whole chain on device and returns only the [B] token ids (the
batch-boundary fetch the decode loop already pays for).

Design:

* The sample is REPARAMETERIZED: the caller supplies per-(row, vocab)
  Gumbel noise (``jax.random.gumbel`` — device-side, generated from the
  engine's PRNG key, never host randomness), and the op is a pure
  deterministic function of (logits, noise). ``argmax(z + gumbel)``
  over the kept set IS a categorical sample over it — so the kernel
  needs no in-kernel RNG and the off-TPU fallback is bit-identical by
  construction (the same math runs in interpret mode / the jnp
  reference).
* Temperature scales the centered logits (f32); top-k and top-p
  restrict the kept set via vectorized THRESHOLD BISECTION (no sort:
  a fixed 24-step binary search per row finds the k-th-largest logit /
  the nucleus probability cutoff — deterministic, branch-free, and
  kernel-friendly). Ties at the threshold are kept (the standard
  "at least k" convention).
* ``temperature == 0`` is greedy and returns ``jnp.argmax(logits, -1)``
  EXACTLY — bit-identical to the argmax the decode step always did.

Dispatch follows the fused_layernorm idiom: a Pallas kernel (one
[rows, V] block per program, f32 accumulation, row block resolved
through the ``sample`` autotune family) inside its `supports()`
envelope (V a lane-tile multiple, rows legal for the (1, bn) token
row); outside it — including the tiny-vocab serving LM — the SAME math
runs as the pure-jnp reference. Off-TPU the kernel runs in interpret
mode, so CPU tier-1 exercises the identical code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deeplearning4j_tpu.ops import autotune

_NEG_INF = -1e30
_BISECT_STEPS = 24


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def supports(batch: int, vocab: int) -> bool:
    """Whether the Pallas kernel's envelope covers a [batch, vocab]
    logits block: lane-tiled vocab, sublane-tiled rows, and a legal
    (1, bn) token-row block (the fused_layernorm stat-row rule)."""
    if vocab % autotune.LANES != 0 or batch % 8 != 0:
        return False
    bn = autotune.sample_rows(batch, vocab)
    return bn % autotune.LANES == 0 or bn == batch


def _select_body(logits, noise, temperature, top_k, top_p):
    """The shared selection math (kernel body AND jnp reference run
    exactly this): centered/temperature-scaled logits, top-k and top-p
    keep-masks via threshold bisection, Gumbel-perturbed argmax.
    logits/noise [bn, V]; returns token ids [bn] int32. f32 throughout."""
    lf = logits.astype(jnp.float32)
    V = lf.shape[-1]
    m = jnp.max(lf, axis=-1, keepdims=True)
    z = (lf - m) / jnp.float32(temperature)            # max row value: 0
    keep = jnp.ones(z.shape, jnp.bool_)
    if top_k and top_k < V:
        # largest threshold t with count(z >= t) >= k: after the
        # bisection `lo` sits just below the k-th largest value, so
        # `z >= lo` keeps the top k (plus exact ties)
        lo = jnp.min(z, axis=-1) - 1.0
        hi = jnp.zeros(z.shape[:-1], jnp.float32) + 1e-6
        for _ in range(_BISECT_STEPS):
            mid = 0.5 * (lo + hi)
            cnt = jnp.sum((z >= mid[..., None]).astype(jnp.float32), -1)
            ge = cnt >= float(top_k)
            lo = jnp.where(ge, mid, lo)
            hi = jnp.where(ge, hi, mid)
        keep = keep & (z >= lo[..., None])
    if top_p and top_p < 1.0:
        e = jnp.exp(z)
        p = e / jnp.sum(e, axis=-1, keepdims=True)
        # largest prob cutoff u with mass({p >= u}) >= top_p: the kept
        # nucleus is the smallest high-prob set reaching top_p mass
        # (the max-prob token always survives: u <= max p)
        lo = jnp.zeros(p.shape[:-1], jnp.float32)
        hi = jnp.max(p, axis=-1) + 1e-6
        for _ in range(_BISECT_STEPS):
            mid = 0.5 * (lo + hi)
            mass = jnp.sum(jnp.where(p >= mid[..., None], p, 0.0), -1)
            ge = mass >= float(top_p)
            lo = jnp.where(ge, mid, lo)
            hi = jnp.where(ge, hi, mid)
        keep = keep & (p >= lo[..., None])
    score = jnp.where(keep, z + noise.astype(jnp.float32), _NEG_INF)
    best = jnp.max(score, axis=-1, keepdims=True)
    # first-match argmax (ties break low, like jnp.argmax): TPU needs
    # the 2D broadcasted iota form
    idx = jax.lax.broadcasted_iota(jnp.int32, score.shape,
                                   len(score.shape) - 1)
    hit = jnp.where(score >= best, idx, V)
    return jnp.min(hit, axis=-1).astype(jnp.int32)


def _sample_kernel(logits_ref, noise_ref, out_ref, *, temperature, top_k,
                   top_p):
    tok = _select_body(logits_ref[...], noise_ref[...], temperature,
                       top_k, top_p)
    out_ref[...] = tok.reshape(out_ref.shape)


def _sample_pallas(logits, noise, temperature, top_k, top_p):
    B, V = logits.shape
    bn = autotune.sample_rows(B, V)
    grid = (B // bn,)
    out = pl.pallas_call(
        functools.partial(_sample_kernel, temperature=temperature,
                          top_k=top_k, top_p=top_p),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, V), lambda i: (i, 0)),
            pl.BlockSpec((bn, V), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bn), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, B), jnp.int32),
        interpret=_use_interpret(),
    )(logits, noise)
    return out[0]


def fused_sample(logits, noise, *, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0):
    """Sample one token id per row of ``logits [B, V]``.

    ``noise [B, V]`` is caller-supplied Gumbel noise (see
    `gumbel_noise`); temperature/top_k/top_p are STATIC Python values
    (they select the compiled program). ``temperature == 0`` ignores
    the noise entirely and is bit-identical to
    ``jnp.argmax(logits, -1)``. Returns [B] int32."""
    if temperature is None or float(temperature) <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    B, V = logits.shape
    if supports(B, V):
        return _sample_pallas(logits, noise, float(temperature),
                              int(top_k or 0), float(top_p or 1.0))
    return _select_body(logits, noise, float(temperature),
                        int(top_k or 0), float(top_p or 1.0))


def gumbel_noise(key, batch: int, vocab: int):
    """Per-(row, vocab) Gumbel noise for `fused_sample` — generated
    device-side from a jax PRNG key (the G004/G024 discipline: no host
    randomness anywhere near the decode loop)."""
    return jax.random.gumbel(key, (batch, vocab), jnp.float32)
