"""Named activation registry.

The reference resolves activations by string name through the ND4J op
executioner (e.g. "sigmoid"/"tanh" in LSTMHelpers.java:155-180, builder
default "sigmoid" at NeuralNetConfiguration.java:339). Here each name maps
to a pure jnp function that XLA fuses into adjacent matmuls — no custom
derivative code is needed anywhere (jax.grad supplies every backward).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _softmax(x):
    # Row-wise softmax over the feature (last) axis, numerically stable.
    return jax.nn.softmax(x, axis=-1)


def _leakyrelu(x):
    return jax.nn.leaky_relu(x, negative_slope=0.01)


def _hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def _hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def _cube(x):
    return x * x * x


def _rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


_REGISTRY = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "leakyrelu": _leakyrelu,
    "softmax": _softmax,
    "identity": lambda x: x,
    "linear": lambda x: x,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "exp": jnp.exp,
    "cube": _cube,
    "hardtanh": _hardtanh,
    "hardsigmoid": _hardsigmoid,
    "rectifiedtanh": _rectifiedtanh,
    "abs": jnp.abs,
    "sqrt": jnp.sqrt,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "sign": jnp.sign,
    "negative": jnp.negative,
    "log": jnp.log,
    "floor": jnp.floor,
    "round": jnp.round,
    "step": lambda x: (x > 0).astype(x.dtype),
}


class Activations:
    """Enum-style constants for the activation names."""

    SIGMOID = "sigmoid"
    TANH = "tanh"
    RELU = "relu"
    LEAKYRELU = "leakyrelu"
    SOFTMAX = "softmax"
    IDENTITY = "identity"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    ELU = "elu"
    GELU = "gelu"
    HARDTANH = "hardtanh"
    CUBE = "cube"


def get_activation(name):
    """Resolve an activation by name. Accepts a callable as passthrough."""
    if callable(name):
        return name
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"Unknown activation '{name}'. Known: {sorted(_REGISTRY)}"
        ) from None


def register_activation(name, fn):
    """Register a custom activation (reference allows custom transforms)."""
    _REGISTRY[name.lower()] = fn
