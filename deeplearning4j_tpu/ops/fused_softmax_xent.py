"""Fused softmax cross-entropy head for large vocabularies (Pallas/TPU).

The stock mcxent path materializes the full [N, V] logits in f32 several
times per step (forward matmul, logsumexp pass, backward p - onehot pass,
then the dx / dW dots re-read it) — at N=16k, V=10k that is ~3 GB of HBM
traffic per training step, measured at ~6.4 ms of an 18.6 ms Transformer-LM
step on v5e. This kernel computes

    loss[n] = logsumexp_v(x[n] @ W + b) - (x[n] @ W + b)[labels[n]]

without ever writing logits to HBM: the forward streams W in vocab chunks
and keeps an online (max, sumexp, label-logit) accumulator in VMEM; the
backward recomputes each logits chunk from (x, W, b, lse) and immediately
contracts p - onehot into dx (one kernel, vocab-chunk inner) and into
dW/db (a second kernel, row-block inner) — the standard
recompute-over-store trade (cf. flash attention, ops/flash_attention.py).

MXU operands stay in the input dtype (bf16 under the TPU dtype policy);
all softmax math and accumulators are f32. Falls back to interpret mode
off-TPU so unit tests exercise the same code on CPU.

Replaces the capability of the reference's fused output-layer delta
(BaseOutputLayer.java computeGradientAndScore computes the softmax/loss
gradient jointly rather than via d(log(softmax))) at TPU scale.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops import autotune
from deeplearning4j_tpu.util.compat import tpu_compiler_params

LANES = autotune.LANES
NEG_INF = -1e30

# Block caps: resolved per (V, d) config through the tuning layer
# (ops/autotune.py — table entry when tuned on TPU, else the swept v5e
# defaults: 1024-row blocks x 2048-wide vocab chunks at d=256 under the
# 32MB scoped limit; see autotune.xent_blocks for the d-scaling rule).
# The names remain as the measured-default record.
BLOCK_N = autotune.DEFAULT_XENT_BLOCK_N
BLOCK_V = autotune.DEFAULT_XENT_BLOCK_V

# Use the fused kernel only where the dense path's [N, V] materialization
# actually hurts; small heads fuse fine inside XLA.
MIN_FUSED_VOCAB = 2048
MAX_FUSED_D = 1024

# Dispatch override: None = auto (TPU only), True = always (interpret mode
# off-TPU — used by unit tests), False = never.
FORCE_FUSED = None


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def supports(n: int, d: int, v: int) -> bool:
    """Whether the fused head handles this shape (else: dense path).

    Ragged row counts are fine — softmax_xent_head pads tokens to the
    128-row grid internally — so `n` does not gate the dispatch."""
    del n
    return v >= MIN_FUSED_VOCAB and d % 128 == 0 and d <= MAX_FUSED_D


# ------------------------------------------------------------------ forward

def _fwd_kernel(x_ref, w_ref, b_ref, lab_ref, loss_ref, lse_ref,
                m_scr, l_scr, ll_scr, *, block_v, n_chunks):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        ll_scr[...] = jnp.zeros_like(ll_scr)

    x = x_ref[...]                                        # [bn, d]
    w = w_ref[...]                                        # [d, bv]
    s = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s + b_ref[...].astype(jnp.float32)                # [bn, bv]

    lab = lab_ref[...]                                    # [bn, 1] int32
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    onehot = cols == lab                                  # [bn, bv]

    m = m_scr[:, 0]
    l = l_scr[:, 0]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)
    l = l * alpha + jnp.sum(jnp.exp(s - m_new[:, None]), axis=-1)
    ll = ll_scr[:, 0] + jnp.sum(jnp.where(onehot, s, 0.0), axis=-1)

    bn = s.shape[0]
    m_scr[...] = jax.lax.broadcast_in_dim(m_new, (bn, LANES), (0,))
    l_scr[...] = jax.lax.broadcast_in_dim(l, (bn, LANES), (0,))
    ll_scr[...] = jax.lax.broadcast_in_dim(ll, (bn, LANES), (0,))

    @pl.when(j == n_chunks - 1)
    def _emit():
        lse = m_new + jnp.log(jnp.maximum(l, 1e-30))
        lse_ref[...] = jax.lax.broadcast_in_dim(lse, (bn, LANES), (0,))
        loss_ref[...] = jax.lax.broadcast_in_dim(lse - ll, (bn, LANES), (0,))


def _fused_fwd(x, w, b, labels, bn, bv):
    N, d = x.shape
    V = w.shape[1]
    n_chunks = V // bv
    lab2 = labels.astype(jnp.int32).reshape(N, 1)
    b2 = b.reshape(1, V)
    kern = functools.partial(_fwd_kernel, block_v=bv, n_chunks=n_chunks)
    loss, lse = pl.pallas_call(
        kern,
        grid=(N // bn, n_chunks),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, bv), lambda i, j: (0, j)),
            pl.BlockSpec((1, bv), lambda i, j: (0, j)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, LANES), jnp.float32),
            jax.ShapeDtypeStruct((N, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, LANES), jnp.float32),
            pltpu.VMEM((bn, LANES), jnp.float32),
            pltpu.VMEM((bn, LANES), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=32 * 1024 * 1024),
        interpret=_use_interpret(),
    )(x, w, b2, lab2)
    return loss[:, 0], lse[:, 0]


# ----------------------------------------------------------------- backward

def _dx_kernel(x_ref, w_ref, b_ref, lab_ref, lse_ref, g_ref, dx_ref,
               acc_scr, *, block_v, n_chunks):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    x = x_ref[...]
    w = w_ref[...]
    s = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s + b_ref[...].astype(jnp.float32)
    lse = lse_ref[:, 0]
    p = jnp.exp(s - lse[:, None])                         # [bn, bv]
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    onehot = cols == lab_ref[...]
    g = (p - jnp.where(onehot, 1.0, 0.0)) * g_ref[:, 0][:, None]
    acc_scr[...] += jax.lax.dot_general(
        g.astype(w.dtype), w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == n_chunks - 1)
    def _emit():
        dx_ref[...] = acc_scr[...].astype(dx_ref.dtype)


def _dwdb_kernel(x_ref, w_ref, b_ref, lab_ref, lse_ref, g_ref, dw_ref,
                 db_ref, dw_scr, db_scr, *, block_v, n_rows):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        dw_scr[...] = jnp.zeros_like(dw_scr)
        db_scr[...] = jnp.zeros_like(db_scr)

    j = pl.program_id(0)
    x = x_ref[...]
    w = w_ref[...]
    s = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s + b_ref[...].astype(jnp.float32)
    lse = lse_ref[:, 0]
    p = jnp.exp(s - lse[:, None])
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    onehot = cols == lab_ref[...]
    g = (p - jnp.where(onehot, 1.0, 0.0)) * g_ref[:, 0][:, None]
    dw_scr[...] += jax.lax.dot_general(
        x, g.astype(x.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    db_scr[...] += jnp.sum(g, axis=0, keepdims=True)

    @pl.when(i == n_rows - 1)
    def _emit():
        dw_ref[...] = dw_scr[...].astype(dw_ref.dtype)
        db_ref[...] = db_scr[...].astype(db_ref.dtype)


def _fused_bwd(bn, bv, res, dloss):
    x, w, b, labels, lse = res
    N, d = x.shape
    V = w.shape[1]
    n_chunks = V // bv
    n_rows = N // bn
    lab2 = labels.astype(jnp.int32).reshape(N, 1)
    b2 = b.reshape(1, V)
    g2 = jax.lax.broadcast_in_dim(
        dloss.astype(jnp.float32), (N, LANES), (0,))
    lse2 = jax.lax.broadcast_in_dim(lse, (N, LANES), (0,))

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, block_v=bv, n_chunks=n_chunks),
        grid=(n_rows, n_chunks),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, bv), lambda i, j: (0, j)),
            pl.BlockSpec((1, bv), lambda i, j: (0, j)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, d), x.dtype),
        scratch_shapes=[pltpu.VMEM((bn, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=32 * 1024 * 1024),
        interpret=_use_interpret(),
    )(x, w, b2, lab2, lse2, g2)

    dw, db2 = pl.pallas_call(
        functools.partial(_dwdb_kernel, block_v=bv, n_rows=n_rows),
        grid=(n_chunks, n_rows),
        in_specs=[
            pl.BlockSpec((bn, d), lambda j, i: (i, 0)),
            pl.BlockSpec((d, bv), lambda j, i: (0, j)),
            pl.BlockSpec((1, bv), lambda j, i: (0, j)),
            pl.BlockSpec((bn, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda j, i: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda j, i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((d, bv), lambda j, i: (0, j)),
            pl.BlockSpec((1, bv), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d, V), w.dtype),
            jax.ShapeDtypeStruct((1, V), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((d, bv), jnp.float32),
            pltpu.VMEM((1, bv), jnp.float32),
        ],
        # the [bn,bv] f32 logits recompute is 8MB alone at the r5 block
        # sizes (bn=1024 x bv=2048), plus the [d,bv] dW scratch and
        # double-buffered weight blocks — well past the conservative
        # 16MB scoped default; v5e has 128MB of VMEM, so all three
        # kernels in this file request 32MB rather than shrinking the
        # swept (faster) block sizes
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=32 * 1024 * 1024),
        interpret=_use_interpret(),
    )(x, w, b2, lab2, lse2, g2)

    # labels are integral: their tangent space is float0, not None
    dlab = np.zeros(labels.shape, jax.dtypes.float0)
    return dx, dw, db2[0].astype(b.dtype), dlab


# block sizes are resolved ONCE in softmax_xent_head (the tuning-table
# key is the UNPADDED (V, d); re-resolving inside the vjp would look up
# the padded vocab and could disagree with the padding bv) and ride the
# custom_vjp as static nondiff args
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused_head(x, w, b, labels, bn, bv):
    loss, _ = _fused_fwd(x, w, b, labels, bn, bv)
    return loss


def _fused_head_fwd(x, w, b, labels, bn, bv):
    loss, lse = _fused_fwd(x, w, b, labels, bn, bv)
    return loss, (x, w, b, labels, lse)


_fused_head.defvjp(_fused_head_fwd, _fused_bwd)


def softmax_xent_head(x, w, b, labels):
    """Per-token softmax cross-entropy of a dense head, fused.

    x: [..., d] features; w: [d, V]; b: [V]; labels: int [...] in [0, V).
    Returns per-token loss [...] (f32). Labels must be in range — mask
    ignored positions via the loss mask, not an ignore index (XLA clamps
    out-of-range gathers; here they would silently hit column V-1).
    """
    lead = x.shape[:-1]
    d = x.shape[-1]
    V = w.shape[-1]
    n = int(np.prod(lead)) if lead else 1
    xf = x.reshape(n, d)
    lf = labels.reshape(n)
    n_pad = (n + 127) // 128 * 128
    if n_pad != n:
        # ragged row counts (e.g. a final partial batch): pad tokens to the
        # 128-row grid; padded rows carry label 0 over zero features, their
        # loss entries are sliced off below, and the slice's VJP gives them
        # zero cotangent so they contribute nothing to dx/dW/db
        xf = jnp.pad(xf, ((0, n_pad - n), (0, 0)))
        lf = jnp.pad(lf, (0, n_pad - n))
    # blocks resolved once against the UNPADDED vocab (the tuning-table
    # key), then the vocab padding below is a whole number of bv chunks
    # by construction
    bn, bv = autotune.xent_blocks(n_pad, d, V)
    if V % bv:
        # pad the vocab to a whole number of chunks; padded columns get
        # bias NEG_INF so exp() kills them, and their dW/db rows are
        # sliced off by the [:, :V] view of the padded weight's cotangent
        vp = (V + bv - 1) // bv * bv
        w = jnp.pad(w, ((0, 0), (0, vp - V)))
        b = jnp.pad(b, (0, vp - V), constant_values=NEG_INF)
    loss = _fused_head(xf, w, b, lf, bn, bv)[:n]
    return loss.reshape(lead)
