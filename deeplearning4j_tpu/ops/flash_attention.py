"""Fused blockwise (flash) attention for TPU via Pallas.

Replaces the naive [B, H, T, T] score materialization in
`nn/layers/attention.dot_product_attention` for the causal/unmasked LM hot
path (the VERDICT-flagged MFU risk): scores never leave VMEM; the softmax
is computed online per key block (running max + running sum), and the
backward pass recomputes probabilities from the saved logsumexp instead of
storing them — O(T) HBM traffic instead of O(T^2).

Kernel layout (per (batch*head group, q-block) program):
  fwd:  loop key blocks -> online softmax into an f32 accumulator; saves
        out and logsumexp.
  bwd:  two kernels — dq (loop over key blocks per q block) and dk/dv
        (loop over q blocks per key block) — using the standard
        ds = p * (dp - delta) identity with delta = rowsum(do * o).

Per-program G-batching: at LM-scale shapes ([B*H, 512, 64]) one (bh,
q-block) program runs ~1us of MXU work against ~2us of fixed program
cost, so the grid is batched G batch-head slices per program (batched
dot_generals amortize the overhead; measured 263us -> 129us per fwd call
at B32 H4 T512 D64 on v5e). G is sized against the 16MB scoped-VMEM
budget and drops to 1 when key/value blocks stream (T > block cap).

Constraints: T divisible by the block size (128), no attention dropout
(the dense path handles it); [B, T] key padding masks fold into the block
predicates, so variable-length batches keep the fused path; head_dim is
padded to the 128-lane tile internally by Mosaic when smaller.

Falls back to interpret mode off-TPU so the unit tests exercise the same
kernel code on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK = 128
LANES = 128  # lane width (used by fused_softmax_xent block sizing)
NEG_INF = -1e30

# Block-size caps (swept on v5e): larger q/k blocks amortize the per-program
# fixed cost and feed the MXU bigger dots; the caps keep scores [bq, bk] f32
# and the full-T K/V copies comfortably inside VMEM.
BLOCK_Q_MAX = 512
BLOCK_K_MAX = 512

# Scoped-VMEM budget a G-batched program's working set must fit. The
# kernels raise their scoped limit to 32MB (v5e has 128MB of VMEM; the
# default 16MB limit rejects G=8, measured the fastest fwd config).
_VMEM_LIMIT = 32 * 1024 * 1024
_VMEM_BUDGET = 26 * 1024 * 1024


def pick_block(n: int, cap: int, base: int = BLOCK) -> int:
    """Largest power-of-two divisor of n up to cap (n % base == 0 assumed).
    Shared by the flash and fused-head kernels for grid-block sizing."""
    b = base
    while b * 2 <= cap and n % (b * 2) == 0:
        b *= 2
    return min(b, n)


def _block_sizes(T):
    return pick_block(T, BLOCK_Q_MAX), pick_block(T, BLOCK_K_MAX)


def _pick_g(BH: int, T: int, D: int, bytes_per_slice: int) -> int:
    """Largest divisor-of-BH group size whose working set fits the scoped
    VMEM budget. G>1 only pays off when per-program work is small (the
    block == T case); callers pass the per-slice byte estimate."""
    g = 1
    for cand in (2, 4, 8):
        if BH % cand == 0 and cand * bytes_per_slice <= _VMEM_BUDGET:
            g = cand
    return g


def _fwd_slice_bytes(T, D):
    # double-buffered q/k/v/o bf16 + scores AND p f32 + f32 acc/carries
    # (measured: the compiled G=8 fwd stack is ~2.6MB per slice at
    # T=512 D=64)
    return 2 * 4 * T * D * 2 + 2 * T * T * 4 + 2 * T * D * 4


def _bwd_slice_bytes(T, D):
    # double-buffered q/k/v/do/dq/dk/dv bf16 + s/p/dp f32 + ds bf16
    return 2 * 7 * T * D * 2 + 3 * T * T * 4 + T * T * 2 + 3 * T * D * 4


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------------------------ forward

def _fwd_kernel(q_ref, k_ref, v_ref, *rest, sm_scale, causal, masked,
                block_q, block_k, seq_len):
    if masked:
        kmask_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    qi = pl.program_id(1)
    # keep the MXU operands in the input dtype (bf16 on TPU runs the MXU at
    # full rate; f32 operands decompose into multiple passes) and accumulate
    # in f32 via preferred_element_type; only softmax math is f32.
    q = q_ref[...]                                         # [G, bq, D]
    G = q.shape[0]
    nk = seq_len // block_k

    if nk == 1 and block_q == seq_len:
        # single-block specialization: a direct softmax (no running
        # max/sum carries, no fori_loop) — the loop+rescale structure
        # costs ~2x at these shapes even when it runs exactly once
        # (measured 286us vs 129us per call at [128,512,64] G=8 on v5e)
        kb = k_ref[...]
        vb = v_ref[...]
        s = sm_scale * jax.lax.dot_general(
            q, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [G, T, T]
        if causal:
            qpos = jax.lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 0)
            kpos = jax.lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 1)
            s = jnp.where((qpos >= kpos)[None], s, NEG_INF)
        if masked:
            s = jnp.where(kmask_ref[:, 0][:, None, :] > 0, s, NEG_INF)
        m = jnp.max(s, axis=-1)
        if masked:
            m = jnp.maximum(m, -1e20)  # all-masked rows underflow to 0
        # exp in the operand dtype (see the backward's note); l is
        # accumulated f32 so the normalizer and lse stay accurate
        p = jnp.exp((s - m[..., None]).astype(vb.dtype))
        l = jnp.maximum(
            jnp.sum(p.astype(jnp.float32), axis=-1), 1e-30)
        acc = jax.lax.dot_general(
            p, vb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        o_ref[...] = (acc / l[..., None]).astype(o_ref.dtype)
        lse_ref[:, 0] = m + jnp.log(l)
        return

    hi = (qi * block_q) // block_k + 1 if causal else nk

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[:, pl.ds(j * block_k, block_k), :]      # [G, bk, D]
        vb = v_ref[:, pl.ds(j * block_k, block_k), :]
        s = sm_scale * jax.lax.dot_general(
            q, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [G, bq, bk]
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where((qpos >= kpos)[None], s, NEG_INF)
        if masked:
            # padding mask gates KEYS (dense-path semantics,
            # nn/layers/attention.dot_product_attention)
            km = kmask_ref[:, 0, pl.ds(j * block_k, block_k)]  # [G, bk]
            s = jnp.where(km[:, None, :] > 0, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        if masked:
            # an all-masked row (fully padded sequence) must not softmax
            # into uniform weights: floor the running max so exp(s - m)
            # underflows to 0 and the l-guard zeroes the output row
            m_new = jnp.maximum(m_new, -1e20)
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [G, bq, D]
        return m_new, l, acc

    D = q_ref.shape[-1]
    m0 = jnp.full((G, block_q), NEG_INF, jnp.float32)
    l0 = jnp.zeros((G, block_q), jnp.float32)
    acc0 = jnp.zeros((G, block_q, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l[..., None]).astype(o_ref.dtype)
    # per-row scalars ride a [G, 1, block_q] block (middle dim equals the
    # array dim, so the (8,128) tile rule is satisfied) — no 128-lane
    # broadcast, which cost ~0.6ms/step of pure HBM traffic in the r2
    # [BH, T, LANES] layout
    lse_ref[:, 0] = m + jnp.log(l)


def _flash_fwd(q, k, v, kmask, sm_scale, causal):
    BH, T, D = q.shape
    block_q, block_k = _block_sizes(T)
    masked = kmask is not None
    G = (_pick_g(BH, T, D, _fwd_slice_bytes(T, D))
         if block_q == T and block_k == T else 1)
    grid = (BH // G, T // block_q)
    kern = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                             masked=masked, block_q=block_q,
                             block_k=block_k, seq_len=T)
    in_specs = [
        pl.BlockSpec((G, block_q, D), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((G, T, D), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((G, T, D), lambda bh, qi: (bh, 0, 0)),
    ]
    args = [q, k, v]
    if masked:
        in_specs.append(pl.BlockSpec((G, 1, T), lambda bh, qi: (bh, 0, 0)))
        args.append(kmask)
    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((G, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((G, 1, block_q), lambda bh, qi: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, T), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=_use_interpret(),
    )(*args)
    return o, lse[:, 0, :]


# ----------------------------------------------------------------- backward

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               sm_scale, causal, masked, block_q, block_k, seq_len):
    if masked:
        kmask_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
    qi = pl.program_id(1)
    q = q_ref[...]                                          # [G, bq, D]
    do = do_ref[...]
    lse = lse_ref[:, 0]                                     # [G, bq]
    delta = delta_ref[:, 0]
    G = q.shape[0]
    nk = seq_len // block_k
    hi = (qi * block_q) // block_k + 1 if causal else nk

    def body(j, dq):
        kb = k_ref[:, pl.ds(j * block_k, block_k), :]
        vb = v_ref[:, pl.ds(j * block_k, block_k), :]
        s = sm_scale * jax.lax.dot_general(
            q, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where((qpos >= kpos)[None], s, NEG_INF)
        if masked:
            km = kmask_ref[:, 0, pl.ds(j * block_k, block_k)]
            s = jnp.where(km[:, None, :] > 0, s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                    # [G, bq, bk]
        dp = jax.lax.dot_general(do, vb, (((2,), (2,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[..., None]) * sm_scale).astype(kb.dtype)
        return dq + jax.lax.dot_general(
            ds, kb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((G, block_q, q_ref.shape[-1]), jnp.float32)
    dq = jax.lax.fori_loop(0, hi, body, dq0)
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                sm_scale, causal, masked, block_q, block_k, seq_len):
    if masked:
        kmask_ref, dk_ref, dv_ref = rest
    else:
        dk_ref, dv_ref = rest
    ki = pl.program_id(1)
    kb = k_ref[...]                                         # [G, bk, D]
    vb = v_ref[...]
    G = kb.shape[0]
    nq = seq_len // block_q
    lo = (ki * block_k) // block_q if causal else 0

    def body(j, carry):
        dk, dv = carry
        qb = q_ref[:, pl.ds(j * block_q, block_q), :]
        dob = do_ref[:, pl.ds(j * block_q, block_q), :]
        lse = lse_ref[:, 0, pl.ds(j * block_q, block_q)]
        delta = delta_ref[:, 0, pl.ds(j * block_q, block_q)]
        s = sm_scale * jax.lax.dot_general(
            qb, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        if causal:
            qpos = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where((qpos >= kpos)[None], s, NEG_INF)
        if masked:
            km = kmask_ref[:, 0]                           # [G, bk]
            s = jnp.where(km[:, None, :] > 0, s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                    # [G, bq, bk]
        dv = dv + jax.lax.dot_general(
            p.astype(dob.dtype), dob, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [G, bk, D]
        dp = jax.lax.dot_general(dob, vb, (((2,), (2,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[..., None]) * sm_scale).astype(qb.dtype)
        dk = dk + jax.lax.dot_general(
            ds, qb, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        return dk, dv

    D = k_ref.shape[-1]
    dk0 = jnp.zeros((G, block_k, D), jnp.float32)
    dv0 = jnp.zeros((G, block_k, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, nq, body, (dk0, dv0))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      *rest, sm_scale, causal, masked, seq_len):
    """Single-pass backward for the block == T case (T <= BLOCK_K_MAX,
    i.e. _block_sizes gave both blocks the whole sequence): with Q, K and
    V all resident, one recompute of the probabilities feeds dq, dk AND
    dv — the two-kernel path recomputes them twice. Grid is (BH/G,); no
    cross-block accumulation exists at this size."""
    if masked:
        kmask_ref, dq_ref, dk_ref, dv_ref = rest
    else:
        dq_ref, dk_ref, dv_ref = rest
    qb = q_ref[...]                                         # [G, T, D]
    dob = do_ref[...]
    kb = k_ref[...]
    vb = v_ref[...]
    lse = lse_ref[:, 0]                                     # [G, T]
    delta = delta_ref[:, 0]
    s = sm_scale * jax.lax.dot_general(
        qb, kb, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)                 # [G, T, T]
    if causal:
        qpos = jax.lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 1)
        s = jnp.where((qpos >= kpos)[None], s, NEG_INF)
    if masked:
        s = jnp.where(kmask_ref[:, 0][:, None, :] > 0, s, NEG_INF)
    # softmax math in the operand dtype: for bf16 models the exp and
    # the ds product run at 2x VPU rate with ~0.4% p error (f32 models
    # keep f32 — the parity tests exercise that path); the MXU consumes
    # p/ds as bf16 regardless
    cdt = kb.dtype
    p = jnp.exp((s - lse[..., None]).astype(cdt))
    dp = jax.lax.dot_general(dob, vb, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    ds = (p * ((dp - delta[..., None]) * sm_scale).astype(cdt))
    dq_ref[...] = jax.lax.dot_general(
        ds, kb, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dv_ref[...] = jax.lax.dot_general(
        p.astype(dob.dtype), dob, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dk_ref[...] = jax.lax.dot_general(
        ds, qb, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def _flash_bwd_fused(q, k, v, do, lse, delta, kmask, sm_scale, causal):
    BH, T, D = q.shape
    masked = kmask is not None
    G = _pick_g(BH, T, D, _bwd_slice_bytes(T, D))
    fullblock = pl.BlockSpec((G, T, D), lambda bh: (bh, 0, 0))
    lblock = pl.BlockSpec((G, 1, T), lambda bh: (bh, 0, 0))
    in_specs = [fullblock, fullblock, fullblock, fullblock, lblock, lblock]
    args = [q, k, v, do, lse, delta]
    if masked:
        in_specs.append(pl.BlockSpec((G, 1, T), lambda bh: (bh, 0, 0)))
        args.append(kmask)
    return pl.pallas_call(
        functools.partial(_bwd_fused_kernel, sm_scale=sm_scale,
                          causal=causal, masked=masked, seq_len=T),
        grid=(BH // G,),
        in_specs=in_specs,
        out_specs=[fullblock, fullblock, fullblock],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=_use_interpret(),
    )(*args)


def _flash_bwd_impl(q, k, v, o, lse, do, kmask, sm_scale, causal):
    BH, T, D = q.shape
    block_q, block_k = _block_sizes(T)
    masked = kmask is not None
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # [BH, 1, T] layout for the per-row scalars (tile-legal via the
    # middle singleton dim) — replaces the r2 [BH, T, LANES] broadcast
    lse = lse[:, None, :]
    delta = delta[:, None, :]

    if block_q == T and block_k == T:
        # whole Q/K/V per program: one fused kernel emits dq, dk and dv
        # from a single probability recompute
        return _flash_bwd_fused(q, k, v, do, lse, delta, kmask, sm_scale,
                                causal)

    dq_specs = [
        pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
        pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
    ]
    dq_args = [q, k, v, do, lse, delta]
    if masked:
        dq_specs.append(pl.BlockSpec((1, 1, T), lambda bh, qi: (bh, 0, 0)))
        dq_args.append(kmask)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          masked=masked, block_q=block_q, block_k=block_k,
                          seq_len=T),
        grid=(BH, T // block_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=_use_interpret(),
    )(*dq_args)

    dkv_specs = [
        pl.BlockSpec((1, T, D), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
        pl.BlockSpec((1, T, D), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, 1, T), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, 1, T), lambda bh, ki: (bh, 0, 0)),
    ]
    dkv_args = [q, k, v, do, lse, delta]
    if masked:
        dkv_specs.append(pl.BlockSpec((1, 1, block_k),
                                      lambda bh, ki: (bh, 0, ki)))
        dkv_args.append(kmask)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          masked=masked, block_q=block_q, block_k=block_k,
                          seq_len=T),
        grid=(BH, T // block_k),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        interpret=_use_interpret(),
    )(*dkv_args)
    return dq, dk, dv


# ---------------------------------------------------------------- public op

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_core(q, k, v, sm_scale, causal):
    o, _ = _flash_fwd(q, k, v, None, sm_scale, causal)
    return o


def _flash_core_fwd(q, k, v, sm_scale, causal):
    o, lse = _flash_fwd(q, k, v, None, sm_scale, causal)
    return o, (q, k, v, o, lse)


def _flash_core_bwd(sm_scale, causal, res, do):
    q, k, v, o, lse = res
    return _flash_bwd_impl(q, k, v, o, lse, do, None, sm_scale, causal)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_core_masked(q, k, v, kmask, sm_scale, causal):
    o, _ = _flash_fwd(q, k, v, kmask, sm_scale, causal)
    return o


def _flash_core_masked_fwd(q, k, v, kmask, sm_scale, causal):
    o, lse = _flash_fwd(q, k, v, kmask, sm_scale, causal)
    return o, (q, k, v, o, lse, kmask)


def _flash_core_masked_bwd(sm_scale, causal, res, do):
    q, k, v, o, lse, kmask = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, o, lse, do, kmask, sm_scale,
                                 causal)
    return dq, dk, dv, jnp.zeros_like(kmask)


_flash_core_masked.defvjp(_flash_core_masked_fwd, _flash_core_masked_bwd)


# Below this sequence length XLA's fused dense attention wins on TPU (the
# kernel's fixed per-program cost dominates once [T,T] traffic is small).
# Measured on v5e with bf16 MXU operands + 512-blocks: flash fwd+bwd beats
# dense 0.84ms vs 1.58ms at T=512 (B32 H4 D64) and 1.3ms vs 14.9ms at
# T=4096, so the crossover sits at or below 512.
MIN_FLASH_SEQ = 512


def supports(q_shape, *, causal, dropout, mask) -> bool:
    """Whether the fused kernel handles this case (else: dense path).
    q_shape is [B, H, T, D] — T at index 2. Padding masks fold into the
    kernels' block predicates (VERDICT r2 #3: variable-length batches keep
    the fused path); attention dropout still routes dense."""
    T = q_shape[2]
    return not dropout and T >= MIN_FLASH_SEQ and T % BLOCK == 0


def flash_attention(q, k, v, *, causal=True, sm_scale=None, mask=None):
    """q, k, v: [B, H, T, D] -> [B, H, T, D]; differentiable (custom VJP).

    mask: optional [B, T] padding mask keyed on KEYS (1 = valid), the
    dense path's semantics (nn/layers/attention.dot_product_attention) —
    masked keys contribute no probability mass and receive zero dk/dv."""
    B, H, T, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    if mask is None:
        o = _flash_core(qf, kf, vf, sm_scale, bool(causal))
    else:
        # [BH, 1, T]: Mosaic block shapes must be (8,128)-divisible or
        # equal to the array dims — the singleton row dim satisfies that
        kmask = jnp.broadcast_to(
            jnp.asarray(mask, jnp.float32)[:, None, :], (B, H, T)
        ).reshape(B * H, 1, T)
        o = _flash_core_masked(qf, kf, vf, kmask, sm_scale, bool(causal))
    return o.reshape(B, H, T, D)
