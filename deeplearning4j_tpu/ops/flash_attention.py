"""Fused blockwise (flash) attention for TPU via Pallas.

Replaces the naive [B, H, T, T] score materialization in
`nn/layers/attention.dot_product_attention` for the causal/unmasked LM hot
path (the VERDICT-flagged MFU risk): scores never leave VMEM; the softmax
is computed online per key block (running max + running sum), and the
backward pass recomputes probabilities from the saved logsumexp instead of
storing them — O(T) HBM traffic instead of O(T^2).

Kernel layout (per (batch*head group, q-block) program):
  fwd:  loop key blocks -> online softmax into an f32 accumulator; saves
        out and logsumexp.
  bwd:  two kernels — dq (loop over key blocks per q block) and dk/dv
        (loop over q blocks per key block) — using the standard
        ds = p * (dp - delta) identity with delta = rowsum(do * o).

Per-program G-batching: at LM-scale shapes ([B*H, 512, 64]) one (bh,
q-block) program runs ~1us of MXU work against ~2us of fixed program
cost, so the grid is batched G batch-head slices per program (batched
dot_generals amortize the overhead; measured 263us -> 129us per fwd call
at B32 H4 T512 D64 on v5e). G is sized against the 16MB scoped-VMEM
budget and drops to 1 when key/value blocks stream (T > block cap).

Constraints: T divisible by the block size (128); [B, T] key padding
masks fold into the block predicates, so variable-length batches keep the
fused path; attention dropout runs IN-KERNEL via a counter-hash keep mask
keyed on GLOBAL (q, k) coordinates (r4, chunk-invariant since r6 — it
composes with the chunked long-context loop and ring hops); head_dim is
padded to the 128-lane tile internally by Mosaic when smaller, and
head_dim % 128 == 0 unlocks the packed-qkv no-relayout entry point
(flash_attention_qkv).

Block sizes, G-batching, and the long-context chunk tile resolve per
config through the tuning layer (ops/autotune.py, r8): a checked-in
TPU-only tuning table with the swept v5e defaults as the deterministic
fallback — graftlint G016 keeps re-frozen literals out of this file.

Falls back to interpret mode off-TPU so the unit tests exercise the same
kernel code on CPU (where the tuning table is inactive, so interpret
results are bit-identical to the defaults).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from deeplearning4j_tpu.ops import autotune
from deeplearning4j_tpu.util.compat import tpu_compiler_params

BLOCK = autotune.BLOCK
LANES = autotune.LANES  # lane width (used by fused_softmax_xent sizing)
NEG_INF = -1e30

# Block-size caps: resolved per config through the tuning layer
# (ops/autotune.py — table entry when tuned on TPU, else the swept v5e
# defaults). These names remain the DISPATCH envelope (supports_qkv's
# single-block bound); per-call grid sizing goes through
# autotune.flash_blocks.
BLOCK_Q_MAX = autotune.DEFAULT_BLOCK_Q_MAX
BLOCK_K_MAX = autotune.DEFAULT_BLOCK_K_MAX

# Scoped-VMEM budget a G-batched program's working set must fit. The
# kernels raise their scoped limit to 32MB (v5e has 128MB of VMEM; the
# default 16MB limit rejects G=8, measured the fastest fwd config).
_VMEM_LIMIT = 32 * 1024 * 1024
_VMEM_BUDGET = 26 * 1024 * 1024


# shared divisor search (moved to the tuning layer in r8; re-exported —
# fused_softmax_xent and the tests import it from here)
pick_block = autotune.pick_block


def _block_sizes(T, D, causal, dropout, masked, kernel):
    """(block_q, block_k) for one monolithic kernel call, resolved
    through the tuning layer: override > TPU table entry > the swept
    512-cap divisor search. Off-TPU the table is inactive, so interpret
    runs keep the deterministic defaults bit-identically."""
    return autotune.flash_blocks(T, D, causal=causal,
                                 dropout=bool(dropout), masked=masked,
                                 kernel=kernel)


def _resolve_g(kernel, BH, T, D, slice_bytes, causal, dropout, masked):
    """Per-program G-batching: a valid tuned G (divides BH) wins, else
    the VMEM-budget heuristic."""
    g = autotune.flash_g(kernel, BH, T, D, causal=causal,
                         dropout=bool(dropout), masked=masked)
    return g if g else _pick_g(BH, T, D, slice_bytes)


def _pick_g(BH: int, T: int, D: int, bytes_per_slice: int) -> int:
    """Largest divisor-of-BH group size whose working set fits the scoped
    VMEM budget. G>1 only pays off when per-program work is small (the
    block == T case); callers pass the per-slice byte estimate."""
    g = 1
    for cand in (2, 4, 8):
        if BH % cand == 0 and cand * bytes_per_slice <= _VMEM_BUDGET:
            g = cand
    return g


def _fwd_slice_bytes(T, D):
    # double-buffered q/k/v/o bf16 + scores AND p f32 + f32 acc/carries
    # (measured: the compiled G=8 fwd stack is ~2.6MB per slice at
    # T=512 D=64)
    return 2 * 4 * T * D * 2 + 2 * T * T * 4 + 2 * T * D * 4


def _bwd_slice_bytes(T, D):
    # double-buffered q/k/v/do/o/dq/dk/dv bf16 + s/p/dp f32 + ds bf16
    # (o streams in since the fused kernel computes delta = rowsum(do*o)
    # in-kernel, r4)
    return 2 * 8 * T * D * 2 + 3 * T * T * 4 + T * T * 2 + 3 * T * D * 4


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------- in-kernel dropout hash
#
# Attention dropout inside the kernels (VERDICT r3 #6) uses a COUNTER-BASED
# hash instead of pltpu.prng_*: the keep decision for score element
# (bh, gq, gk) is murmur3-fmix32 of its absolute coordinates + the step
# seed, so every kernel (fwd/dq/dkv/fused, any block size or G-batching)
# regenerates the identical mask, and CPU interpret mode matches TPU
# bit-for-bit (pltpu's PRNG is a zero-stub under interpret). ~10 u32 VPU
# ops per element — noise next to the exp.

def _fmix32(x):
    u = jnp.uint32
    x = x ^ (x >> u(16))
    x = x * u(0x85EBCA6B)
    x = x ^ (x >> u(13))
    x = x * u(0xC2B2AE35)
    x = x ^ (x >> u(16))
    return x


def _keep_mask(seed, bh0, stride, G, q0, k0, bq, bk, hash_t, rate):
    """[G, bq, bk] bool keep mask. seed: traced scalar; bh0: this
    program's first absolute batch*head row; stride: bh step between the
    G slices; q0/k0: GLOBAL row/col offsets of the block in the full
    sequence (may be traced); hash_t: the GLOBAL sequence length used as
    the row stride of the linearized hash coordinate. Keying on global
    (q0, k0, hash_t) makes the keep decision for logical element
    (bh, i, j) CHUNK-INVARIANT: a tile computed at origin (q0, k0) of a
    length-hash_t sequence drops exactly what the monolithic kernel at
    T=hash_t would — the chunked flash loop and the ring's per-hop
    kernels regenerate identical masks (r6).

    The per-ROW key gets the full murmur finalizer (cheap: G values);
    the per-ELEMENT mix is the shorter mul/xorshift/mul/xorshift tail —
    the full fmix32 per element cost ~0.09 ms per layer fwd+bwd pair at
    the r5 bench shapes (hash VPU ops, measured), and with a well-mixed
    key the shorter tail keeps the keep-fraction / row-balance /
    adjacency-decorrelation statistics (measured corr < 0.003;
    test_dropout_statistics_and_determinism)."""
    u = jnp.uint32
    bh = (jnp.asarray(bh0).astype(jnp.uint32)
          + jax.lax.broadcasted_iota(jnp.uint32, (G, 1, 1), 0) * u(stride))
    key = _fmix32(seed.astype(jnp.uint32) + bh * u(0x9E3779B9))
    gq = (jnp.asarray(q0).astype(jnp.uint32)
          + jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 0))
    gk = (jnp.asarray(k0).astype(jnp.uint32)
          + jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 1))
    h = key + (gq * u(hash_t) + gk)[None]
    h = h * u(0xCC9E2D51)
    h = h ^ (h >> u(15))
    h = h * u(0x1B873593)
    h = h ^ (h >> u(13))
    thr = u(min(int((1.0 - rate) * 4294967296.0), 4294967295))
    return h < thr


def dropout_keep_mask_host(seed, bh, T, rate):
    """NumPy twin of the kernels' keep mask for one bh slice: [T, T]
    bool. Test oracle — reconstructs the exact in-kernel mask."""
    def fmix(x):
        x = np.uint32(x).copy()
        x ^= x >> np.uint32(16)
        x = (x * np.uint32(0x85EBCA6B)).astype(np.uint32)
        x ^= x >> np.uint32(13)
        x = (x * np.uint32(0xC2B2AE35)).astype(np.uint32)
        x ^= x >> np.uint32(16)
        return x

    with np.errstate(over="ignore"):
        key = fmix(np.uint32(seed) + np.uint32(bh) * np.uint32(0x9E3779B9))
        gq, gk = np.meshgrid(np.arange(T, dtype=np.uint32),
                             np.arange(T, dtype=np.uint32), indexing="ij")
        h = (key + gq * np.uint32(T) + gk).astype(np.uint32)
        h = (h * np.uint32(0xCC9E2D51)).astype(np.uint32)
        h ^= h >> np.uint32(15)
        h = (h * np.uint32(0x1B873593)).astype(np.uint32)
        h ^= h >> np.uint32(13)
        thr = np.uint32(min(int((1.0 - rate) * 4294967296.0), 4294967295))
    return h < thr


def _step_seed(dropout_rng):
    """[1, 1] int32 per-step dropout key derived from a jax PRNG key."""
    return jax.random.randint(dropout_rng, (1, 1), 0, 2**31 - 1,
                              dtype=jnp.int32)


def _drop_ctx(seed, q_origin=0, k_origin=0):
    """[1, 3] int32 dropout-context operand the kernels read: (step seed,
    global q origin, global k origin) — the absolute sequence offsets of
    this kernel call's window. `seed` is the [1, 1] int32 step key;
    origins may be Python ints (the unrolled chunk loop) or traced
    scalars (ring hops, whose k origin depends on the hop index)."""
    orig = jnp.stack([jnp.asarray(q_origin, jnp.int32).reshape(()),
                      jnp.asarray(k_origin, jnp.int32).reshape(())])
    return jnp.concatenate([jnp.reshape(seed, (1, 1)), orig[None]], axis=1)


# ------------------------------------------------------------------ forward

def _attn_single_block(q, kb, vb, km, keep_scale_vals, sm_scale, causal,
                       seq_len):
    """Whole-sequence attention for one G-batched slice: q/kb/vb
    [G, T, D], km [G, T] key mask or None, keep_scale_vals [G, T, T]
    dropout keep*1/(1-r) or None. Returns (o [G, T, D] f32-normalized,
    lse [G, T]). Shared by the flat/packed kernels and the D=64
    head-pair kernel."""
    s = sm_scale * jax.lax.dot_general(
        q, kb, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)                # [G, T, T]
    if causal:
        qpos = jax.lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 1)
        s = jnp.where((qpos >= kpos)[None], s, NEG_INF)
    if km is not None:
        s = jnp.where(km[:, None, :] > 0, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    if km is not None:
        m = jnp.maximum(m, -1e20)  # all-masked rows underflow to 0
    # exp in the operand dtype (see the backward's note); l is
    # accumulated f32 so the normalizer and lse stay accurate
    p = jnp.exp((s - m[..., None]).astype(vb.dtype))
    l = jnp.maximum(jnp.sum(p.astype(jnp.float32), axis=-1), 1e-30)
    pd = p
    if keep_scale_vals is not None:
        # drop normalized-attention mass: l comes from the UNDROPPED
        # p (dense semantics: dropout applies to softmax output)
        pd = p * keep_scale_vals.astype(p.dtype)
    acc = jax.lax.dot_general(
        pd, vb, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    return acc / l[..., None], m + jnp.log(l)


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, sm_scale, causal, masked,
                block_q, block_k, seq_len, dropout=0.0, bh_stride=1,
                packed_heads=False, hash_t=None):
    rest = list(rest)
    kmask_ref = rest.pop(0) if masked else None
    seed_ref = rest.pop(0) if dropout else None
    o_ref, lse_ref = rest
    qi = pl.program_id(1)
    if dropout:
        G_ = q_ref.shape[0]
        # absolute batch*head row of this program's first slice. Flat
        # grid (BH//G, nq): rows are pid0*G..+G-1 (stride 1). Packed grid
        # (B//G, H): batch b = pid0*G + g at head pid1 -> row b*H + pid1
        # (stride H) — the SAME (b*H + h) numbering as the flat layout,
        # so the host oracle and the flat kernels reproduce the mask.
        bh0 = pl.program_id(0) * G_ * bh_stride
        if packed_heads:
            bh0 = bh0 + pl.program_id(1)
        # chunk-invariance (r6): the ctx operand carries the window's
        # global (q, k) origin; hash_t is the GLOBAL sequence length —
        # per-chunk/per-hop calls hash the same coordinates the
        # monolithic kernel would
        qo, ko = seed_ref[0, 1], seed_ref[0, 2]

        def keep_scale(q0, k0, bq, bk):
            keep = _keep_mask(seed_ref[0, 0], bh0, bh_stride, G_,
                              qo + q0, ko + k0, bq, bk,
                              hash_t or seq_len, dropout)
            return keep.astype(jnp.float32) * (1.0 / (1.0 - dropout))
    # keep the MXU operands in the input dtype (bf16 on TPU runs the MXU at
    # full rate; f32 operands decompose into multiple passes) and accumulate
    # in f32 via preferred_element_type; only softmax math is f32.
    q = q_ref[...]                                         # [G, bq, D]
    G = q.shape[0]
    nk = seq_len // block_k

    if nk == 1 and block_q == seq_len:
        # single-block specialization: a direct softmax (no running
        # max/sum carries, no fori_loop) — the loop+rescale structure
        # costs ~2x at these shapes even when it runs exactly once
        # (measured 286us vs 129us per call at [128,512,64] G=8 on v5e)
        kb = k_ref[...]
        vb = v_ref[...]
        km = kmask_ref[:, 0] if masked else None
        o, lse = _attn_single_block(
            q, kb, vb, km, keep_scale(0, 0, seq_len, seq_len)
            if dropout else None, sm_scale, causal, seq_len)
        o_ref[...] = o.astype(o_ref.dtype)
        # reshape-write keeps this branch layout-agnostic: the flat path
        # passes a [G, 1, T] lse block, the packed-qkv path [G, 1, 1, T]
        lse_ref[...] = lse.reshape(lse_ref.shape)
        return

    # last key block the q block's LAST row reaches — correct for any
    # block_q/block_k ratio (the pre-r8 `qi*bq//bk + 1` silently dropped
    # key blocks when a tuned block_q exceeded block_k; equal blocks,
    # the default, reduce to the same value bit-for-bit)
    hi = ((qi + 1) * block_q - 1) // block_k + 1 if causal else nk

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[:, pl.ds(j * block_k, block_k), :]      # [G, bk, D]
        vb = v_ref[:, pl.ds(j * block_k, block_k), :]
        s = sm_scale * jax.lax.dot_general(
            q, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [G, bq, bk]
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where((qpos >= kpos)[None], s, NEG_INF)
        if masked:
            # padding mask gates KEYS (dense-path semantics,
            # nn/layers/attention.dot_product_attention)
            km = kmask_ref[:, 0, pl.ds(j * block_k, block_k)]  # [G, bk]
            s = jnp.where(km[:, None, :] > 0, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        if masked:
            # an all-masked row (fully padded sequence) must not softmax
            # into uniform weights: floor the running max so exp(s - m)
            # underflows to 0 and the l-guard zeroes the output row
            m_new = jnp.maximum(m_new, -1e20)
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        pd = p
        if dropout:
            pd = p * keep_scale(qi * block_q, j * block_k,
                                block_q, block_k)
        acc = acc * alpha[..., None] + jax.lax.dot_general(
            pd.astype(vb.dtype), vb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [G, bq, D]
        return m_new, l, acc

    D = q_ref.shape[-1]
    m0 = jnp.full((G, block_q), NEG_INF, jnp.float32)
    l0 = jnp.zeros((G, block_q), jnp.float32)
    acc0 = jnp.zeros((G, block_q, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l[..., None]).astype(o_ref.dtype)
    # per-row scalars ride a [G, 1, block_q] block (middle dim equals the
    # array dim, so the (8,128) tile rule is satisfied) — no 128-lane
    # broadcast, which cost ~0.6ms/step of pure HBM traffic in the r2
    # [BH, T, LANES] layout
    lse_ref[:, 0] = m + jnp.log(l)


def _flash_fwd(q, k, v, kmask, sm_scale, causal, dropout=0.0, seed=None,
               hash_t=None):
    BH, T, D = q.shape
    masked = kmask is not None
    block_q, block_k = _block_sizes(T, D, causal, dropout, masked,
                                    "flash_fwd")
    extra = int(T * T * 4) if dropout else 0  # f32 keep mask per slice
    G = (_resolve_g("flash_fwd", BH, T, D,
                    _fwd_slice_bytes(T, D) + extra, causal, dropout,
                    masked)
         if block_q == T and block_k == T else 1)
    grid = (BH // G, T // block_q)
    kern = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                             masked=masked, block_q=block_q,
                             block_k=block_k, seq_len=T, dropout=dropout,
                             hash_t=hash_t)
    in_specs = [
        pl.BlockSpec((G, block_q, D), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((G, T, D), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((G, T, D), lambda bh, qi: (bh, 0, 0)),
    ]
    args = [q, k, v]
    if masked:
        in_specs.append(pl.BlockSpec((G, 1, T), lambda bh, qi: (bh, 0, 0)))
        args.append(kmask)
    if dropout:
        in_specs.append(pl.BlockSpec((1, 3), lambda bh, qi: (0, 0)))
        args.append(seed)
    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((G, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((G, 1, block_q), lambda bh, qi: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, T), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=_use_interpret(),
    )(*args)
    return o, lse[:, 0, :]


# ----------------------------------------------------------------- backward

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               sm_scale, causal, masked, block_q, block_k, seq_len,
               dropout=0.0, bh_stride=1, hash_t=None):
    rest = list(rest)
    kmask_ref = rest.pop(0) if masked else None
    seed_ref = rest.pop(0) if dropout else None
    (dq_ref,) = rest
    qi = pl.program_id(1)
    # program_id must be read OUTSIDE the fori_loop body (interpret mode
    # cannot lower it from inside the loop's closed jaxpr)
    bh0 = pl.program_id(0) if dropout else None
    qo = seed_ref[0, 1] if dropout else None  # global window origin (r6)
    ko = seed_ref[0, 2] if dropout else None
    q = q_ref[...]                                          # [G, bq, D]
    do = do_ref[...]
    lse = lse_ref[:, 0]                                     # [G, bq]
    delta = delta_ref[:, 0]
    G = q.shape[0]
    nk = seq_len // block_k
    # see _fwd_kernel's bound note: reach the LAST row's key block
    hi = ((qi + 1) * block_q - 1) // block_k + 1 if causal else nk

    def body(j, dq):
        kb = k_ref[:, pl.ds(j * block_k, block_k), :]
        vb = v_ref[:, pl.ds(j * block_k, block_k), :]
        s = sm_scale * jax.lax.dot_general(
            q, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where((qpos >= kpos)[None], s, NEG_INF)
        if masked:
            km = kmask_ref[:, 0, pl.ds(j * block_k, block_k)]
            s = jnp.where(km[:, None, :] > 0, s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                    # [G, bq, bk]
        dp = jax.lax.dot_general(do, vb, (((2,), (2,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        if dropout:
            ks = _keep_mask(seed_ref[0, 0], bh0 * G * bh_stride,
                            bh_stride, G, qo + qi * block_q,
                            ko + j * block_k, block_q, block_k,
                            hash_t or seq_len, dropout).astype(jnp.float32)
            dp = dp * (ks * (1.0 / (1.0 - dropout)))
        ds = (p * (dp - delta[..., None]) * sm_scale).astype(kb.dtype)
        return dq + jax.lax.dot_general(
            ds, kb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((G, block_q, q_ref.shape[-1]), jnp.float32)
    dq = jax.lax.fori_loop(0, hi, body, dq0)
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                sm_scale, causal, masked, block_q, block_k, seq_len,
                dropout=0.0, bh_stride=1, hash_t=None):
    rest = list(rest)
    kmask_ref = rest.pop(0) if masked else None
    seed_ref = rest.pop(0) if dropout else None
    dk_ref, dv_ref = rest
    ki = pl.program_id(1)
    bh0 = pl.program_id(0) if dropout else None  # see _dq_kernel note
    qo = seed_ref[0, 1] if dropout else None
    ko = seed_ref[0, 2] if dropout else None
    kb = k_ref[...]                                         # [G, bk, D]
    vb = v_ref[...]
    G = kb.shape[0]
    nq = seq_len // block_q
    lo = (ki * block_k) // block_q if causal else 0

    def body(j, carry):
        dk, dv = carry
        qb = q_ref[:, pl.ds(j * block_q, block_q), :]
        dob = do_ref[:, pl.ds(j * block_q, block_q), :]
        lse = lse_ref[:, 0, pl.ds(j * block_q, block_q)]
        delta = delta_ref[:, 0, pl.ds(j * block_q, block_q)]
        s = sm_scale * jax.lax.dot_general(
            qb, kb, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        if causal:
            qpos = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where((qpos >= kpos)[None], s, NEG_INF)
        if masked:
            km = kmask_ref[:, 0]                           # [G, bk]
            s = jnp.where(km[:, None, :] > 0, s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                    # [G, bq, bk]
        pd = p
        dp = jax.lax.dot_general(dob, vb, (((2,), (2,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        if dropout:
            ks = _keep_mask(seed_ref[0, 0], bh0 * G * bh_stride,
                            bh_stride, G, qo + j * block_q,
                            ko + ki * block_k, block_q, block_k,
                            hash_t or seq_len, dropout).astype(jnp.float32)
            ks = ks * (1.0 / (1.0 - dropout))
            pd = p * ks
            dp = dp * ks
        dv = dv + jax.lax.dot_general(
            pd.astype(dob.dtype), dob, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [G, bk, D]
        ds = (p * (dp - delta[..., None]) * sm_scale).astype(qb.dtype)
        dk = dk + jax.lax.dot_general(
            ds, qb, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        return dk, dv

    D = k_ref.shape[-1]
    dk0 = jnp.zeros((G, block_k, D), jnp.float32)
    dv0 = jnp.zeros((G, block_k, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, nq, body, (dk0, dv0))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _attn_single_block_bwd(qb, kb, vb, dob, ob, lse, km, ks, dlse,
                           sm_scale, causal, seq_len):
    """Whole-sequence fused backward for one G-batched slice: recomputes
    p from lse, returns (dq, dk, dv) [G, T, D] f32. km: [G, T] key mask
    or None; ks: [G, T, T] dropout keep*1/(1-r) or None; dlse: [G, T]
    ring-lse cotangent or None. Shared by the flat/packed fused-backward
    kernels and the D=64 head-pair kernel."""
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1)                                # [G, T]
    if dlse is not None:
        delta = delta - dlse
    s = sm_scale * jax.lax.dot_general(
        qb, kb, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)                 # [G, T, T]
    if causal:
        qpos = jax.lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (seq_len, seq_len), 1)
        s = jnp.where((qpos >= kpos)[None], s, NEG_INF)
    if km is not None:
        s = jnp.where(km[:, None, :] > 0, s, NEG_INF)
    # softmax math in the operand dtype: for bf16 models the exp and
    # the ds product run at 2x VPU rate with ~0.4% p error (f32 models
    # keep f32 — the parity tests exercise that path); the MXU consumes
    # p/ds as bf16 regardless
    cdt = kb.dtype
    p = jnp.exp((s - lse[..., None]).astype(cdt))
    pd = p
    dp = jax.lax.dot_general(dob, vb, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    if ks is not None:
        pd = p * ks.astype(cdt)
        dp = dp * ks
    ds = (p * ((dp - delta[..., None]) * sm_scale).astype(cdt))
    dq = jax.lax.dot_general(
        ds, kb, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    dv = jax.lax.dot_general(
        pd.astype(dob.dtype), dob, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    dk = jax.lax.dot_general(
        ds, qb, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    return dq, dk, dv


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                      *rest, sm_scale, causal, masked, seq_len,
                      dropout=0.0, bh_stride=1, has_dlse=False,
                      packed_heads=False, hash_t=None):
    """Single-pass backward for the block == T case (T <= BLOCK_K_MAX,
    i.e. _block_sizes gave both blocks the whole sequence): with Q, K and
    V all resident, one recompute of the probabilities feeds dq, dk AND
    dv — the two-kernel path recomputes them twice. Grid is (BH/G,); no
    cross-block accumulation exists at this size. delta = rowsum(do*o)
    is computed IN-KERNEL (r4: the host-side delta pass cost ~0.6 ms/step
    of reduce+relayout traffic on the packed layout); an optional dlse
    operand (ring-attention lse cotangent) subtracts from it."""
    rest = list(rest)
    kmask_ref = rest.pop(0) if masked else None
    seed_ref = rest.pop(0) if dropout else None
    dlse_ref = rest.pop(0) if has_dlse else None
    dq_ref, dk_ref, dv_ref = rest
    qb = q_ref[...]                                         # [G, T, D]
    dob = do_ref[...]
    kb = k_ref[...]
    vb = v_ref[...]
    G = qb.shape[0]
    lse = lse_ref[...].reshape(G, seq_len)                  # [G, T]
    ks = None
    if dropout:
        bh0 = pl.program_id(0) * G * bh_stride
        if packed_heads:
            bh0 = bh0 + pl.program_id(1)  # see _fwd_kernel's numbering
        ks = _keep_mask(seed_ref[0, 0], bh0, bh_stride, G,
                        seed_ref[0, 1], seed_ref[0, 2], seq_len,
                        seq_len, hash_t or seq_len,
                        dropout).astype(jnp.float32)
        ks = ks * (1.0 / (1.0 - dropout))
    dq, dk, dv = _attn_single_block_bwd(
        qb, kb, vb, dob, o_ref[...], lse,
        kmask_ref[:, 0] if masked else None, ks,
        dlse_ref[...].reshape(G, seq_len) if has_dlse else None,
        sm_scale, causal, seq_len)
    dq_ref[...] = dq.astype(dq_ref.dtype)
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd_fused(q, k, v, do, o, lse, kmask, sm_scale, causal,
                     dropout=0.0, seed=None, dlse=None, hash_t=None):
    BH, T, D = q.shape
    masked = kmask is not None
    extra = int(T * T * 4) if dropout else 0
    G = _resolve_g("flash_bwd", BH, T, D, _bwd_slice_bytes(T, D) + extra,
                   causal, dropout, masked)
    fullblock = pl.BlockSpec((G, T, D), lambda bh: (bh, 0, 0))
    lblock = pl.BlockSpec((G, 1, T), lambda bh: (bh, 0, 0))
    in_specs = [fullblock, fullblock, fullblock, fullblock, fullblock,
                lblock]
    args = [q, k, v, do, o, lse]
    if masked:
        in_specs.append(pl.BlockSpec((G, 1, T), lambda bh: (bh, 0, 0)))
        args.append(kmask)
    if dropout:
        in_specs.append(pl.BlockSpec((1, 3), lambda bh: (0, 0)))
        args.append(seed)
    if dlse is not None:
        in_specs.append(lblock)
        args.append(dlse)
    return pl.pallas_call(
        functools.partial(_bwd_fused_kernel, sm_scale=sm_scale,
                          causal=causal, masked=masked, seq_len=T,
                          dropout=dropout, has_dlse=dlse is not None,
                          hash_t=hash_t),
        grid=(BH // G,),
        in_specs=in_specs,
        out_specs=[fullblock, fullblock, fullblock],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        compiler_params=tpu_compiler_params(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=_use_interpret(),
    )(*args)


def _flash_bwd_impl(q, k, v, o, lse, do, kmask, sm_scale, causal,
                    dlse=None, dropout=0.0, seed=None, hash_t=None):
    BH, T, D = q.shape
    masked = kmask is not None
    block_q, block_k = _block_sizes(T, D, causal, dropout, masked,
                                    "flash_bwd")

    if block_q == T and block_k == T:
        # whole Q/K/V per program: one fused kernel emits dq, dk and dv
        # from a single probability recompute; delta = rowsum(do*o) (and
        # the optional ring dlse fold) happens in-kernel
        return _flash_bwd_fused(
            q, k, v, do, o, lse[:, None, :], kmask, sm_scale, causal,
            dropout=dropout, seed=seed, hash_t=hash_t,
            dlse=None if dlse is None else
            dlse.astype(jnp.float32)[:, None, :])

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        # lse cotangent (ring-attention merge weights differentiate
        # through lse): d lse/d s = p, so ds = p*(dp - delta + dlse) —
        # folding -dlse into delta reuses the kernels unchanged
        delta = delta - dlse.astype(jnp.float32)
    # [BH, 1, T] layout for the per-row scalars (tile-legal via the
    # middle singleton dim) — replaces the r2 [BH, T, LANES] broadcast
    lse = lse[:, None, :]
    delta = delta[:, None, :]

    dq_specs = [
        pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
        pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
    ]
    dq_args = [q, k, v, do, lse, delta]
    if masked:
        dq_specs.append(pl.BlockSpec((1, 1, T), lambda bh, qi: (bh, 0, 0)))
        dq_args.append(kmask)
    if dropout:
        dq_specs.append(pl.BlockSpec((1, 3), lambda bh, qi: (0, 0)))
        dq_args.append(seed)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          masked=masked, block_q=block_q, block_k=block_k,
                          seq_len=T, dropout=dropout, hash_t=hash_t),
        grid=(BH, T // block_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=_use_interpret(),
    )(*dq_args)

    dkv_specs = [
        pl.BlockSpec((1, T, D), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
        pl.BlockSpec((1, T, D), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, 1, T), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, 1, T), lambda bh, ki: (bh, 0, 0)),
    ]
    dkv_args = [q, k, v, do, lse, delta]
    if masked:
        dkv_specs.append(pl.BlockSpec((1, 1, block_k),
                                      lambda bh, ki: (bh, 0, ki)))
        dkv_args.append(kmask)
    if dropout:
        dkv_specs.append(pl.BlockSpec((1, 3), lambda bh, ki: (0, 0)))
        dkv_args.append(seed)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          masked=masked, block_q=block_q, block_k=block_k,
                          seq_len=T, dropout=dropout, hash_t=hash_t),
        grid=(BH, T // block_k),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        interpret=_use_interpret(),
    )(*dkv_args)
    return dq, dk, dv


# ---------------------------------------------------------------- public op

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_core(q, k, v, sm_scale, causal):
    o, _ = _flash_fwd(q, k, v, None, sm_scale, causal)
    return o


def _flash_core_fwd(q, k, v, sm_scale, causal):
    o, lse = _flash_fwd(q, k, v, None, sm_scale, causal)
    return o, (q, k, v, o, lse)


def _flash_core_bwd(sm_scale, causal, res, do):
    q, k, v, o, lse = res
    return _flash_bwd_impl(q, k, v, o, lse, do, None, sm_scale, causal)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_core_masked(q, k, v, kmask, sm_scale, causal):
    o, _ = _flash_fwd(q, k, v, kmask, sm_scale, causal)
    return o


def _flash_core_masked_fwd(q, k, v, kmask, sm_scale, causal):
    o, lse = _flash_fwd(q, k, v, kmask, sm_scale, causal)
    return o, (q, k, v, o, lse, kmask)


def _flash_core_masked_bwd(sm_scale, causal, res, do):
    q, k, v, o, lse, kmask = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, o, lse, do, kmask, sm_scale,
                                 causal)
    return dq, dk, dv, jnp.zeros_like(kmask)


_flash_core_masked.defvjp(_flash_core_masked_fwd, _flash_core_masked_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash_core_drop(q, k, v, kmask, seed, sm_scale, causal, dropout):
    """Dropout-enabled core (kmask always an operand — pass ones when
    there is no padding mask; seed: [1,3] int32 dropout ctx from
    _drop_ctx)."""
    o, _ = _flash_fwd(q, k, v, kmask, sm_scale, causal, dropout=dropout,
                      seed=seed)
    return o


def _flash_core_drop_fwd(q, k, v, kmask, seed, sm_scale, causal, dropout):
    o, lse = _flash_fwd(q, k, v, kmask, sm_scale, causal, dropout=dropout,
                        seed=seed)
    return o, (q, k, v, o, lse, kmask, seed)


def _flash_core_drop_bwd(sm_scale, causal, dropout, res, do):
    q, k, v, o, lse, kmask, seed = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, o, lse, do, kmask, sm_scale,
                                 causal, dropout=dropout, seed=seed)
    # int primals take a float0 cotangent (zero_from_primal), not an int
    # zeros array — custom_vjp's cotangent check enforces this
    return (dq, dk, dv, jnp.zeros_like(kmask),
            jax.custom_derivatives.zero_from_primal(seed))


_flash_core_drop.defvjp(_flash_core_drop_fwd, _flash_core_drop_bwd)


# --------------------------------------------- (o, lse) core for ring hops

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_lse(q, k, v, sm_scale, causal):
    """Flat-layout flash returning BOTH outputs: (o [BH, T, D], lse
    [BH, T]) — differentiable in o AND lse. This is the per-hop primitive
    of ring attention (parallel/ring_attention.py): each hop's normalized
    block result merges with the carry via the two-way lse combine, whose
    weights need d(lse) to flow. Requires T % 128 == 0."""
    return _flash_fwd(q, k, v, None, sm_scale, causal)


def _fal_fwd(q, k, v, sm_scale, causal):
    o, lse = _flash_fwd(q, k, v, None, sm_scale, causal)
    return (o, lse), (q, k, v, o, lse)


def _fal_bwd(sm_scale, causal, res, cts):
    do, dlse = cts
    q, k, v, o, lse = res
    return _flash_bwd_impl(q, k, v, o, lse, do, None, sm_scale, causal,
                           dlse=dlse)


flash_attention_lse.defvjp(_fal_fwd, _fal_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def flash_attention_lse_masked(q, k, v, kmask, sm_scale, causal):
    """flash_attention_lse with a [BH, 1, T] key padding mask operand —
    the per-tile primitive of the MASKED chunk loop
    (chunked_flash_attention_lse): each kv tile sees its slice of the
    mask, so variable-length batches keep the fused path at chunked
    lengths. A fully-masked tile emits lse ~ -1e20 and a zero block,
    which the lse merge weights away."""
    o, lse = _flash_fwd(q, k, v, kmask, sm_scale, causal)
    return o, lse


def _falm_fwd(q, k, v, kmask, sm_scale, causal):
    o, lse = _flash_fwd(q, k, v, kmask, sm_scale, causal)
    return (o, lse), (q, k, v, kmask, o, lse)


def _falm_bwd(sm_scale, causal, res, cts):
    do, dlse = cts
    q, k, v, kmask, o, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, o, lse, do, kmask, sm_scale,
                                 causal, dlse=dlse)
    return dq, dk, dv, jnp.zeros_like(kmask)


flash_attention_lse_masked.defvjp(_falm_fwd, _falm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def flash_attention_lse_drop(q, k, v, kmask, ctx, sm_scale, causal,
                             dropout, hash_t):
    """flash_attention_lse_masked + in-kernel dropout whose keep mask is
    keyed on GLOBAL coordinates (r6): ctx is the [1, 3] int32 dropout
    context from `_drop_ctx` (step seed, q origin, k origin) and hash_t
    the GLOBAL sequence length, so a tile at origin (q0, k0) drops
    exactly the elements the monolithic kernel at T=hash_t would. This
    is the per-tile primitive of the dropout-enabled chunk loop
    (chunked_flash_attention_lse) and the ring's dropout hops
    (parallel/ring_attention.py). kmask is always an operand — pass ones
    when unpadded."""
    return _flash_fwd(q, k, v, kmask, sm_scale, causal, dropout=dropout,
                      seed=ctx, hash_t=hash_t)


def _fald_fwd(q, k, v, kmask, ctx, sm_scale, causal, dropout, hash_t):
    o, lse = _flash_fwd(q, k, v, kmask, sm_scale, causal, dropout=dropout,
                        seed=ctx, hash_t=hash_t)
    return (o, lse), (q, k, v, kmask, ctx, o, lse)


def _fald_bwd(sm_scale, causal, dropout, hash_t, res, cts):
    do, dlse = cts
    q, k, v, kmask, ctx, o, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, o, lse, do, kmask, sm_scale,
                                 causal, dlse=dlse, dropout=dropout,
                                 seed=ctx, hash_t=hash_t)
    return (dq, dk, dv, jnp.zeros_like(kmask),
            jax.custom_derivatives.zero_from_primal(ctx))


flash_attention_lse_drop.defvjp(_fald_fwd, _fald_bwd)


# ------------------------------------------------- packed-qkv (no relayout)
#
# When head_dim is a multiple of the 128-lane tile, the kernels can read
# Q/K/V STRAIGHT out of the [B, T, 3n] projection output — BlockSpecs
# slice the head's D-column window (legal: the last block dim is a
# multiple of 128) — and write the output back in [B, T, n]. The
# [B,T,H,D]->[B,H,T,D] head transposes and their backward twins (~0.9
# ms/step at the r4 bench shapes) disappear entirely. Scope: the
# single-block regime (T <= BLOCK_Q_MAX) that covers the T=512 flagship;
# longer sequences keep the flat [B*H, T, D] streaming path.


def _fwd_kernel_pair(q_ref, k_ref, v_ref, *rest, sm_scale, causal, masked,
                     seq_len, dropout=0.0, n_heads=2):
    """Head-PAIR forward for D=64: each program reads a 128-lane column
    slice spanning two adjacent heads (the lane-tile rule forbids 64-wide
    BlockSpecs) and runs the single-block attention per head. The two
    64-wide dots still fill only half the MXU contraction — inherent to
    D=64 — but the [B,T,H,D]<->[B,H,T,D] HBM relayouts and their backward
    twins disappear, and G-batching amortizes program cost."""
    rest = list(rest)
    kmask_ref = rest.pop(0) if masked else None
    seed_ref = rest.pop(0) if dropout else None
    o_ref, lse_ref = rest
    G = q_ref.shape[0]
    km = kmask_ref[:, 0] if masked else None
    os, lses = [], []
    for hh in range(2):
        sl = slice(hh * 64, hh * 64 + 64)
        keep = None
        if dropout:
            # absolute row b*H + (2*pid1 + hh) — the flat-layout numbering
            bh0 = (pl.program_id(0) * G * n_heads
                   + 2 * pl.program_id(1) + hh)
            keep = (_keep_mask(seed_ref[0, 0], bh0, n_heads, G, 0, 0,
                               seq_len, seq_len, seq_len, dropout)
                    .astype(jnp.float32) * (1.0 / (1.0 - dropout)))
        o, lse = _attn_single_block(
            q_ref[:, :, sl], k_ref[:, :, sl], v_ref[:, :, sl], km, keep,
            sm_scale, causal, seq_len)
        os.append(o)
        lses.append(lse)
    o_ref[...] = jnp.concatenate(os, axis=-1).astype(o_ref.dtype)
    lse_ref[...] = jnp.stack(lses, axis=1)[:, :, None, :]


def _bwd_kernel_pair(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, *rest,
                     sm_scale, causal, masked, seq_len, dropout=0.0,
                     n_heads=2):
    rest = list(rest)
    kmask_ref = rest.pop(0) if masked else None
    seed_ref = rest.pop(0) if dropout else None
    dq_ref, dk_ref, dv_ref = rest
    G = q_ref.shape[0]
    km = kmask_ref[:, 0] if masked else None
    lse_pair = lse_ref[...]                                 # [G, 2, 1, T]
    dqs, dks, dvs = [], [], []
    for hh in range(2):
        sl = slice(hh * 64, hh * 64 + 64)
        ks = None
        if dropout:
            bh0 = (pl.program_id(0) * G * n_heads
                   + 2 * pl.program_id(1) + hh)
            ks = (_keep_mask(seed_ref[0, 0], bh0, n_heads, G, 0, 0,
                             seq_len, seq_len, seq_len, dropout)
                  .astype(jnp.float32) * (1.0 / (1.0 - dropout)))
        dq, dk, dv = _attn_single_block_bwd(
            q_ref[:, :, sl], k_ref[:, :, sl], v_ref[:, :, sl],
            do_ref[:, :, sl], o_ref[:, :, sl],
            lse_pair[:, hh, 0, :], km, ks, None, sm_scale, causal,
            seq_len)
        dqs.append(dq)
        dks.append(dk)
        dvs.append(dv)
    dq_ref[...] = jnp.concatenate(dqs, axis=-1).astype(dq_ref.dtype)
    dk_ref[...] = jnp.concatenate(dks, axis=-1).astype(dk_ref.dtype)
    dv_ref[...] = jnp.concatenate(dvs, axis=-1).astype(dv_ref.dtype)


def _flash_fwd_qkv_pair(qkv, H, kmask, sm_scale, causal, dropout=0.0,
                        seed=None):
    B, T, three_n = qkv.shape
    n = three_n // 3
    HP = H // 2
    masked = kmask is not None
    extra = int(T * T * 4) if dropout else 0
    G = _resolve_g("flash_fwd_qkv_pair", B, T, LANES,
                   _fwd_slice_bytes(T, LANES) + extra, causal, dropout,
                   masked)
    kern = functools.partial(_fwd_kernel_pair, sm_scale=sm_scale,
                             causal=causal, masked=masked, seq_len=T,
                             dropout=dropout, n_heads=H)
    # column blocks are 128 wide: q pair hp sits at block hp, k at
    # HP + hp, v at 2*HP + hp (block indices in 128-lane units)
    in_specs = [
        pl.BlockSpec((G, T, 128), lambda b, hp: (b, 0, hp)),
        pl.BlockSpec((G, T, 128), lambda b, hp: (b, 0, HP + hp)),
        pl.BlockSpec((G, T, 128), lambda b, hp: (b, 0, 2 * HP + hp)),
    ]
    args = [qkv, qkv, qkv]
    if masked:
        in_specs.append(pl.BlockSpec((G, 1, T), lambda b, hp: (b, 0, 0)))
        args.append(kmask)
    if dropout:
        in_specs.append(pl.BlockSpec((1, 3), lambda b, hp: (0, 0)))
        args.append(seed)
    o, lse = pl.pallas_call(
        kern,
        grid=(B // G, HP),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((G, T, 128), lambda b, hp: (b, 0, hp)),
            pl.BlockSpec((G, 2, 1, T), lambda b, hp: (b, hp, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, n), qkv.dtype),
            jax.ShapeDtypeStruct((B, H, 1, T), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=_use_interpret(),
    )(*args)
    return o, lse


def _flash_bwd_qkv_pair(qkv, o, lse, do, H, kmask, sm_scale, causal,
                        dropout=0.0, seed=None):
    B, T, three_n = qkv.shape
    n = three_n // 3
    HP = H // 2
    masked = kmask is not None
    extra = int(T * T * 4) if dropout else 0
    G = _resolve_g("flash_bwd_qkv_pair", B, T, LANES,
                   _bwd_slice_bytes(T, LANES) + extra, causal, dropout,
                   masked)
    col = pl.BlockSpec((G, T, 128), lambda b, hp: (b, 0, hp))
    in_specs = [
        col,
        pl.BlockSpec((G, T, 128), lambda b, hp: (b, 0, HP + hp)),
        pl.BlockSpec((G, T, 128), lambda b, hp: (b, 0, 2 * HP + hp)),
        col,                                                # do pair
        col,                                                # o pair
        pl.BlockSpec((G, 2, 1, T), lambda b, hp: (b, hp, 0, 0)),
    ]
    args = [qkv, qkv, qkv, do, o, lse]
    if masked:
        in_specs.append(pl.BlockSpec((G, 1, T), lambda b, hp: (b, 0, 0)))
        args.append(kmask)
    if dropout:
        in_specs.append(pl.BlockSpec((1, 3), lambda b, hp: (0, 0)))
        args.append(seed)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel_pair, sm_scale=sm_scale,
                          causal=causal, masked=masked, seq_len=T,
                          dropout=dropout, n_heads=H),
        grid=(B // G, HP),
        in_specs=in_specs,
        out_specs=[col, col, col],
        out_shape=[jax.ShapeDtypeStruct((B, T, n), qkv.dtype)] * 3,
        compiler_params=tpu_compiler_params(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=_use_interpret(),
    )(*args)
    return jnp.concatenate([dq, dk, dv], axis=-1)


def _flash_fwd_qkv(qkv, H, kmask, sm_scale, causal, dropout=0.0, seed=None):
    B, T, three_n = qkv.shape
    n = three_n // 3
    D = n // H
    if D == 64:
        return _flash_fwd_qkv_pair(qkv, H, kmask, sm_scale, causal,
                                   dropout=dropout, seed=seed)
    masked = kmask is not None
    extra = int(T * T * 4) if dropout else 0  # f32 keep mask per slice
    G = _resolve_g("flash_fwd_qkv", B, T, D,
                   _fwd_slice_bytes(T, D) + extra, causal, dropout,
                   masked)
    grid = (B // G, H)
    kern = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                             masked=masked, block_q=T, block_k=T, seq_len=T,
                             dropout=dropout, bh_stride=H, packed_heads=True)
    in_specs = [
        pl.BlockSpec((G, T, D), lambda b, h: (b, 0, h)),           # q cols
        pl.BlockSpec((G, T, D), lambda b, h: (b, 0, H + h)),       # k cols
        pl.BlockSpec((G, T, D), lambda b, h: (b, 0, 2 * H + h)),   # v cols
    ]
    args = [qkv, qkv, qkv]
    if masked:
        in_specs.append(pl.BlockSpec((G, 1, T), lambda b, h: (b, 0, 0)))
        args.append(kmask)
    if dropout:
        in_specs.append(pl.BlockSpec((1, 3), lambda b, h: (0, 0)))
        args.append(seed)
    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((G, T, D), lambda b, h: (b, 0, h)),
            pl.BlockSpec((G, 1, 1, T), lambda b, h: (b, h, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, n), qkv.dtype),
            jax.ShapeDtypeStruct((B, H, 1, T), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=_use_interpret(),
    )(*args)
    return o, lse


def _flash_bwd_qkv(qkv, o, lse, do, H, kmask, sm_scale, causal,
                   dropout=0.0, seed=None):
    B, T, three_n = qkv.shape
    n = three_n // 3
    D = n // H
    if D == 64:
        return _flash_bwd_qkv_pair(qkv, o, lse, do, H, kmask, sm_scale,
                                   causal, dropout=dropout, seed=seed)
    masked = kmask is not None
    extra = int(T * T * 4) if dropout else 0
    G = _resolve_g("flash_bwd_qkv", B, T, D,
                   _bwd_slice_bytes(T, D) + extra, causal, dropout,
                   masked)
    rows = pl.BlockSpec((G, 1, 1, T), lambda b, h: (b, h, 0, 0))
    col = pl.BlockSpec((G, T, D), lambda b, h: (b, 0, h))
    in_specs = [
        col,                                                       # q
        pl.BlockSpec((G, T, D), lambda b, h: (b, 0, H + h)),       # k
        pl.BlockSpec((G, T, D), lambda b, h: (b, 0, 2 * H + h)),   # v
        col,                                                       # do cols
        col,                                                       # o cols
        rows,
    ]
    # delta = rowsum(do*o) happens in-kernel from the o column slice —
    # the host-side per-head reduce + [B,T,H]->[B,H,1,T] relayout cost
    # ~0.6 ms/step at the r4 flagship shapes
    args = [qkv, qkv, qkv, do, o, lse]
    if masked:
        in_specs.append(pl.BlockSpec((G, 1, T), lambda b, h: (b, 0, 0)))
        args.append(kmask)
    if dropout:
        in_specs.append(pl.BlockSpec((1, 3), lambda b, h: (0, 0)))
        args.append(seed)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, sm_scale=sm_scale,
                          causal=causal, masked=masked, seq_len=T,
                          dropout=dropout, bh_stride=H, packed_heads=True),
        grid=(B // G, H),
        in_specs=in_specs,
        out_specs=[col, col, col],
        out_shape=[jax.ShapeDtypeStruct((B, T, n), qkv.dtype)] * 3,
        compiler_params=tpu_compiler_params(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=_use_interpret(),
    )(*args)
    return jnp.concatenate([dq, dk, dv], axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _flash_qkv_core(qkv, H, sm_scale, causal):
    o, _ = _flash_fwd_qkv(qkv, H, None, sm_scale, causal)
    return o


def _flash_qkv_core_fwd(qkv, H, sm_scale, causal):
    o, lse = _flash_fwd_qkv(qkv, H, None, sm_scale, causal)
    return o, (qkv, o, lse)


def _flash_qkv_core_bwd(H, sm_scale, causal, res, do):
    qkv, o, lse = res
    return (_flash_bwd_qkv(qkv, o, lse, do, H, None, sm_scale, causal),)


_flash_qkv_core.defvjp(_flash_qkv_core_fwd, _flash_qkv_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _flash_qkv_core_masked(qkv, kmask, H, sm_scale, causal):
    o, _ = _flash_fwd_qkv(qkv, H, kmask, sm_scale, causal)
    return o


def _flash_qkv_core_masked_fwd(qkv, kmask, H, sm_scale, causal):
    o, lse = _flash_fwd_qkv(qkv, H, kmask, sm_scale, causal)
    return o, (qkv, o, lse, kmask)


def _flash_qkv_core_masked_bwd(H, sm_scale, causal, res, do):
    qkv, o, lse, kmask = res
    dqkv = _flash_bwd_qkv(qkv, o, lse, do, H, kmask, sm_scale, causal)
    return dqkv, jnp.zeros_like(kmask)


_flash_qkv_core_masked.defvjp(_flash_qkv_core_masked_fwd,
                              _flash_qkv_core_masked_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_qkv_core_drop(qkv, kmask, seed, H, sm_scale, causal, dropout):
    """Dropout-enabled packed core (r5 — VERDICT r4 #2: the dropout
    config no longer falls off the no-relayout path). kmask is always an
    operand (ones when unpadded); seed: [1,3] int32 dropout ctx."""
    o, _ = _flash_fwd_qkv(qkv, H, kmask, sm_scale, causal,
                          dropout=dropout, seed=seed)
    return o


def _flash_qkv_core_drop_fwd(qkv, kmask, seed, H, sm_scale, causal,
                             dropout):
    o, lse = _flash_fwd_qkv(qkv, H, kmask, sm_scale, causal,
                            dropout=dropout, seed=seed)
    return o, (qkv, o, lse, kmask, seed)


def _flash_qkv_core_drop_bwd(H, sm_scale, causal, dropout, res, do):
    qkv, o, lse, kmask, seed = res
    dqkv = _flash_bwd_qkv(qkv, o, lse, do, H, kmask, sm_scale, causal,
                          dropout=dropout, seed=seed)
    return (dqkv, jnp.zeros_like(kmask),
            jax.custom_derivatives.zero_from_primal(seed))


_flash_qkv_core_drop.defvjp(_flash_qkv_core_drop_fwd,
                            _flash_qkv_core_drop_bwd)


def supports_qkv(B, T, n, H, *, dropout) -> bool:
    """Envelope of the packed no-relayout path: head_dim a lane-tile
    multiple — or exactly 64 with an even head count (head-PAIR column
    slices, r5 — the config users actually run, VERDICT r4 #5) — single-
    block sequence length, head count dividing a G-batchable batch.
    Attention dropout runs in-kernel on this path too (r5)."""
    if n % H:
        return False
    D = n // H
    dim_ok = D % 128 == 0 or (D == 64 and H % 2 == 0)
    return dim_ok and MIN_FLASH_SEQ <= T <= BLOCK_Q_MAX and T % BLOCK == 0


def flash_attention_qkv(qkv, n_heads, *, causal=True, sm_scale=None,
                        mask=None, dropout=0.0, dropout_rng=None):
    """Packed-projection attention: qkv [B, T, 3n] (the x @ Wqkv output,
    q|k|v each n = H*D wide) -> out [B, T, n], never materializing a
    [B, H, T, D] relayout. Check `supports_qkv` first. dropout masks are
    generated in-kernel from the same (b*H + h) counter-hash stream as
    the flat layout, so both paths drop identical score elements for a
    given rng."""
    B, T, three_n = qkv.shape
    n = three_n // 3
    D = n // n_heads
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))
    if dropout:
        if dropout_rng is None:
            raise ValueError("dropout > 0 requires dropout_rng")
        ctx = _drop_ctx(_step_seed(dropout_rng))
        kmask = (jnp.ones((B, 1, T), jnp.float32) if mask is None
                 else jnp.asarray(mask, jnp.float32)[:, None, :])
        return _flash_qkv_core_drop(qkv, kmask, ctx, n_heads, sm_scale,
                                    bool(causal), float(dropout))
    if mask is None:
        return _flash_qkv_core(qkv, n_heads, sm_scale, bool(causal))
    kmask = jnp.asarray(mask, jnp.float32)[:, None, :]      # [B, 1, T]
    return _flash_qkv_core_masked(qkv, kmask, n_heads, sm_scale,
                                  bool(causal))


# Below this sequence length XLA's fused dense attention wins on TPU (the
# kernel's fixed per-program cost dominates once [T,T] traffic is small).
# Measured on v5e with bf16 MXU operands + 512-blocks: flash fwd+bwd beats
# dense 0.84ms vs 1.58ms at T=512 (B32 H4 D64) and 1.3ms vs 14.9ms at
# T=4096, so the crossover sits at or below 512.
MIN_FLASH_SEQ = 512

# Largest T the monolithic long-T kernels are performance-proven at: the
# dq/dkv backward streams full-T K/V (resp. Q/dO) blocks through VMEM
# (double-buffered bf16 [T, D] pairs), which fits at 8192 (0.69 MFU
# in-model) and busts VMEM at 15360+ with 512-blocks. Beyond this,
# attention prefers chunked_flash_attention — same kernels over
# chunk-length tiles.
MAX_FLASH_T = 8192

# Hard compile ceiling of the monolithic backward (measured at D=128,
# 512-blocks: 14336 compiles, 15360 fails). T in (MAX_FLASH_T,
# MONOLITHIC_COMPILE_MAX] that the tile loop cannot take — padding
# masks, attention dropout, or a non-tileable length — falls back to the
# monolithic kernels (the pre-r5 behavior for every such config) instead
# of raising.
MONOLITHIC_COMPILE_MAX = 14336


def supports(q_shape, *, causal, dropout, mask) -> bool:
    """Whether the MONOLITHIC fused kernel handles this case. q_shape is
    [B, H, T, D] — T at index 2. Padding masks fold into the kernels'
    block predicates (VERDICT r2 #3); attention dropout runs IN-KERNEL
    via the counter-hash keep mask (VERDICT r3 #6), so dropout configs
    keep the fused path too. T above MAX_FLASH_T: see supports_chunked."""
    T = q_shape[2]
    return MIN_FLASH_SEQ <= T <= MAX_FLASH_T and T % BLOCK == 0


# What must be bounded is the TRACE SIZE of the chunk loop — the pallas
# calls one jaxpr accumulates — and since r8 that depends on causality
# STRUCTURALLY, not just in pair count: causal rows mix full and
# diagonal-causal tiles, so the (q_i, kv_j) pairs stay Python-unrolled
# and the budget is the PAIR count (136 = the causal 16-chunk budget the
# seq-131072 config measured at 0.70 MFU with tolerable compile time).
# Non-causal rows are UNIFORM (every tile full), so their kv loop is a
# lax.scan — ONE traced kernel per q chunk — and the budget is the CHUNK
# count. ADVICE r5 #1's n^2 unroll (16 non-causal chunks = 256 forward
# calls + VJPs) is structurally gone; an uncapped awkward T (e.g.
# 25088 -> 49 chunks of 512) would still unroll 1200+ causal pallas
# calls, hence the caps.
MAX_CHUNKS = 16
MAX_CHUNK_PAIRS = MAX_CHUNKS * (MAX_CHUNKS + 1) // 2  # 136


def chunk_pairs(n: int, causal: bool) -> int:
    """RUNTIME tile-pair kernel launches of an n-chunk loop. For causal
    this is also the trace size; non-causal pairs run under a scan (see
    traced_tile_calls)."""
    return n * (n + 1) // 2 if causal else n * n


def traced_tile_calls(n: int, causal: bool) -> int:
    """Pallas calls the n-chunk loop traces into ONE jaxpr — the
    compile-size unit the budgets bound. Causal unrolls every pair;
    non-causal scans the kv tiles, so one traced kernel per q chunk."""
    return chunk_pairs(n, True) if causal else n


def _fits_unroll(n: int, causal: bool) -> bool:
    if causal:
        return chunk_pairs(n, causal) <= MAX_CHUNK_PAIRS
    return n <= MAX_CHUNKS


def max_chunks(causal: bool) -> int:
    """Largest chunk count whose trace size fits the budget: 16 both
    ways since r8 (the causal 16-chunk unroll is the original 136-pair
    budget; non-causal kv loops scan instead of unrolling)."""
    n = MAX_CHUNKS
    while n > 1 and not _fits_unroll(n, causal):
        n -= 1
    return n


# Kernel-proven tile lengths, largest first — owned by the tuning layer
# (autotune.CHUNK_TILES), re-exported as the envelope quoted in error
# messages (chunked_unsupported_reason, the ring hop dispatch). The
# usable cap shrinks with head_dim (autotune.max_tile_for_dim): the
# backward streams full-tile [T, D] K/V pairs, so D=256 proves tiles to
# 4096, D=512 to 2048 — the D>128 long-T tier ADVICE r5 #2 asked for.
CHUNK_TILES = autotune.CHUNK_TILES


def pick_chunk(T: int, causal: bool = True, head_dim: int | None = None) \
        -> int:
    """Largest kernel-proven tile length (within the D-aware bound when
    `head_dim` is given) that divides T into 2+ chunks fitting the trace
    budget (0: T not chunkable). Tiles are tried largest-first, so the
    dispatch prefers FEWER, larger chunks."""
    cap = autotune.max_tile_for_dim(head_dim)
    for c in CHUNK_TILES:
        if c > cap:
            continue
        if T % c == 0 and 2 <= T // c and _fits_unroll(T // c, causal):
            return c
    return 0


def _tiles_str(head_dim=None) -> str:
    cap = autotune.max_tile_for_dim(head_dim)
    return "/".join(str(c) for c in reversed(CHUNK_TILES) if c <= cap)


def supports_chunked(q_shape, *, causal, dropout, mask) -> bool:
    """Envelope of the blockwise long-context path: T beyond the
    monolithic kernels, divisible into kernel-proven tiles (D-aware —
    head dims past 128 use shorter tiles, r8) whose trace size fits the
    budget (causality-aware — causal pairs unroll, non-causal kv tiles
    scan). Padding masks ride the loop (each kv tile sees its mask
    slice — flash_attention_lse_masked); attention dropout rides it too
    (r6: the keep mask hashes GLOBAL (q, k) coordinates through
    flash_attention_lse_drop, so every tile regenerates exactly the
    monolithic kernel's mask)."""
    T, D = q_shape[2], q_shape[3]
    return T > MAX_FLASH_T and pick_chunk(T, causal, head_dim=D) > 0


def supports_monolithic_fallback(q_shape, *, causal, dropout, mask) -> bool:
    """T in (MAX_FLASH_T, MONOLITHIC_COMPILE_MAX] the tile loop cannot
    take (non-tileable lengths) still compiles on the monolithic kernels
    with every in-kernel feature — the pre-r5 dispatch for those shapes,
    kept so they don't regress to an error. Gated at D <= 128: the
    compile ceiling was measured there, and the backward's VMEM working
    set scales with D — D > 128 long-T routes through the chunked tier's
    D-aware tiles instead (supports_chunked, r8)."""
    T, D = q_shape[2], q_shape[3]
    return (MAX_FLASH_T < T <= MONOLITHIC_COMPILE_MAX and T % BLOCK == 0
            and D <= 128)


def servable_seq(T: int, head_dim: int, *, causal: bool = True,
                 dropout: bool = False, mask: bool = True) -> bool:
    """Whether a [*, H, T, head_dim] attention shape has SOME compilable
    path — the envelope the serving bucket lattice validates against
    (serving/buckets.py) before warmup freezes its shapes. T at or below
    MAX_FLASH_T always compiles (fused kernels where the shape
    qualifies, the dense einsum fallback otherwise); beyond it the shape
    must fit the chunked tier or the monolithic-fallback tier, else the
    attention layer raises chunked_unsupported_reason mid-traffic."""
    if T <= MAX_FLASH_T:
        return True
    shape = (1, 1, T, head_dim)
    return (supports_chunked(shape, causal=causal, dropout=dropout,
                             mask=mask)
            or supports_monolithic_fallback(shape, causal=causal,
                                            dropout=dropout, mask=mask))


def chunked_unsupported_reason(T, *, dropout, mask, causal=True,
                               head_dim=None) -> str:
    """Why a long-T shape has no fused path — raised by the attention
    layer so long-context misconfigurations fail with instructions
    instead of a dense-path device OOM. Dropout is NOT an exclusion
    anymore (r6) and neither are non-causal lengths up to 16 tiles (r8:
    scanned kv loops) nor head dims past 128 (r8: D-aware tile bound);
    what remains is tile-divisibility under those bounds, plus the
    D <= 128 gate on the monolithic fallback tier."""
    nmax = max_chunks(causal)
    cap = autotune.max_tile_for_dim(head_dim)
    msg = (f"attention at T={T} cannot be tiled: the chunked flash path "
           f"needs T divisible into 2-{nmax} "
           f"{'causal' if causal else 'non-causal'} tiles of "
           f"{_tiles_str(head_dim)}")
    if head_dim and head_dim > 128:
        msg += (f" (head_dim={head_dim} caps tiles at {cap}: the "
                "backward's VMEM working set scales with head_dim)")
    msg += (f" (causal trace budget {MAX_CHUNK_PAIRS} unrolled tile "
            f"pairs, non-causal kv tiles scan at {MAX_CHUNKS} chunks "
            f"max; max single-chip T here = {nmax * cap})")
    if T <= MONOLITHIC_COMPILE_MAX:
        msg += (f", and the monolithic fallback (T <= "
                f"{MONOLITHIC_COMPILE_MAX}) requires head_dim <= 128"
                + (f" — got head_dim={head_dim}" if head_dim else ""))
    return msg + (" — pad T to a tile-divisible length or shard T over a "
                  "'seq' mesh axis (ring attention)")


def lse_combine(o, lse, o_hop, lse_hop):
    """Two-way logsumexp merge of normalized attention partials: carry
    (o [.., T, D] f32, lse [.., T]) absorbs a hop's (o_hop, lse_hop).
    The single numerics home for BOTH the serial chunk loop
    (chunked_flash_attention) and the cross-device ring
    (parallel/ring_attention.py) — f32 accumulate, 1e-30 denom floor."""
    m = jnp.maximum(lse, lse_hop)
    a, b = jnp.exp(lse - m), jnp.exp(lse_hop - m)
    denom = jnp.maximum(a + b, 1e-30)
    o = (o * a[..., None]
         + o_hop.astype(jnp.float32) * b[..., None]) / denom[..., None]
    return o, m + jnp.log(denom)


def chunked_flash_attention(q, k, v, *, causal=True, sm_scale=None,
                            mask=None, chunk=None, dropout=0.0,
                            dropout_rng=None):
    """Single-chip long-context attention: Q/KV cut into chunk-length
    tiles, each (q_i, kv_j) pair running the monolithic Pallas kernel
    (j < i full, j == i causal diagonal, j > i skipped), results merged
    with the two-way logsumexp combine — the SAME per-hop primitive +
    merge ring attention uses across devices (parallel/ring_attention.py),
    serialized on one chip. VMEM stays bounded by the tile length, so any
    chunk-divisible T compiles; HBM never holds [T, T] anything.

    q, k, v: [B, H, T, D] -> [B, H, T, D]; differentiable (the lse-merge
    weights flow through flash_attention_lse's custom VJP). mask:
    optional [B, T] key padding mask (1 = valid), sliced per kv tile.
    dropout: attention-weight dropout generated in-kernel from
    `dropout_rng` — chunk-invariant (r6): each tile hashes its GLOBAL
    (q, k) coordinates, so the keep mask equals the monolithic kernel's
    at this T bit-for-bit. `chunk` defaults to pick_chunk(T, causal)."""
    B, H, T, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))
    kmask = None if mask is None else _broadcast_kmask(mask, B, H, T)
    seed = None
    if dropout:
        if dropout_rng is None:
            raise ValueError("dropout > 0 requires dropout_rng")
        seed = _step_seed(dropout_rng)
    o, _ = chunked_flash_attention_lse(
        q.reshape(B * H, T, D), k.reshape(B * H, T, D),
        v.reshape(B * H, T, D), sm_scale, causal, kmask=kmask, chunk=chunk,
        dropout=dropout, seed=seed)
    return o.reshape(B, H, T, D)


def chunked_flash_attention_lse(q, k, v, sm_scale, causal, kmask=None,
                                chunk=None, dropout=0.0, seed=None,
                                q_origin=0, k_origin=0, hash_t=None):
    """Flat-layout chunked attention returning (o [BH, T, D], lse
    [BH, T]) — the long-local-block form of flash_attention_lse: ring
    hops whose PER-SHARD block exceeds MAX_FLASH_T route here
    (parallel/ring_attention.py), so the seq mesh axis composes with
    single-chip chunking to sequences of n_shards * 128k tokens.
    Differentiable the same way (per-tile custom VJPs + lse_combine).
    kmask: optional [BH, 1, T] key padding mask, sliced per kv tile.

    dropout/seed: in-kernel dropout (seed from _step_seed) whose keep
    mask hashes GLOBAL coordinates — q_origin/k_origin are this call's
    window offsets in the full sequence (nonzero for ring hops; may be
    traced) and hash_t the GLOBAL sequence length (defaults to T), so
    the mask is invariant to the chunk count AND to how the sequence is
    sharded across ring hops."""
    BH, T, D = q.shape

    # explicit/tuned chunks obey the same guards as pick_chunk:
    # lane-legal tiles no longer than the D-aware proven envelope, with
    # a trace size inside the budget (an uncapped hop_chunk would
    # compile for minutes; an oversized one would hand the monolithic
    # kernel the VMEM-busting length this path avoids)
    def _fits(cand):
        return (isinstance(cand, int) and cand > 0 and T % cand == 0
                and cand % BLOCK == 0
                and cand <= autotune.max_tile_for_dim(D)
                and T // cand >= 2 and _fits_unroll(T // cand, causal))

    c = chunk
    if not c:
        c = (autotune.chunk_tile(T, D, causal=causal,
                                 dropout=bool(dropout),
                                 masked=kmask is not None, fits=_fits)
             or pick_chunk(T, causal, head_dim=D))
    n = T // c if c else 0
    if not _fits(c):
        raise ValueError(
            f"T={T} not divisible into 2-{max_chunks(causal)} kernel tiles"
            + (f" of {chunk}" if chunk else "")
            + (f" ({chunk_pairs(n, causal)} unrolled tile pairs exceed "
               f"the {MAX_CHUNK_PAIRS} budget)"
               if n >= 2 and not _fits_unroll(n, causal) else "")
            + (f" (head_dim={D} caps tiles at "
               f"{autotune.max_tile_for_dim(D)})"
               if c and c % BLOCK == 0 and n >= 2
               and c > autotune.max_tile_for_dim(D) else ""))
    ht = hash_t if hash_t is not None else T
    km = kmask
    if dropout and km is None:
        # the dropout cores take kmask unconditionally (ones = unpadded)
        km = jnp.ones((BH, 1, T), jnp.float32)
    if not causal:
        return _chunked_noncausal(q, k, v, sm_scale, c, n, km, dropout,
                                  seed, q_origin, k_origin, ht)
    outs, lses = [], []
    for i in range(n):
        qi = q[:, i * c:(i + 1) * c]
        o = lse = None
        for j in range(i + 1):
            kj = k[:, j * c:(j + 1) * c]
            vj = v[:, j * c:(j + 1) * c]
            if dropout:
                ctx = _drop_ctx(seed, q_origin + i * c, k_origin + j * c)
                o_hop, lse_hop = flash_attention_lse_drop(
                    qi, kj, vj, km[:, :, j * c:(j + 1) * c], ctx,
                    sm_scale, j == i, float(dropout), ht)
            elif km is None:
                o_hop, lse_hop = flash_attention_lse(
                    qi, kj, vj, sm_scale, j == i)
            else:
                o_hop, lse_hop = flash_attention_lse_masked(
                    qi, kj, vj, km[:, :, j * c:(j + 1) * c],
                    sm_scale, j == i)
            if o is None:
                # stay in the kernel dtype until a merge NEEDS f32 — a
                # single-hop row (i == 0 causal) otherwise round-trips
                # bf16 -> f32 -> bf16 for nothing (graftlint P003)
                o, lse = o_hop, lse_hop
            else:
                o, lse = lse_combine(o.astype(jnp.float32), lse,
                                     o_hop, lse_hop)
        outs.append(o.astype(q.dtype))
        lses.append(lse)
    return jnp.concatenate(outs, axis=1), jnp.concatenate(lses, axis=1)


def _chunked_noncausal(q, k, v, sm_scale, c, n, km, dropout, seed,
                       q_origin, k_origin, hash_t):
    """Non-causal chunk loop: kv tiles are UNIFORM (every (q_i, kv_j)
    pair runs the full kernel — no diagonal specialization), so the
    inner loop is a lax.scan over stacked kv tiles — ONE traced kernel
    per q chunk instead of the n^2 Python unroll ADVICE r5 #1 flagged
    (16 chunks would have unrolled 256 forward calls plus their VJPs).
    Numerics match the unrolled loop bit-for-bit: the carry starts at
    (0, NEG_INF), whose first lse_combine is exact (a = exp(NEG_INF -
    lse_hop) underflows to 0.0, b = exp(0) = 1.0, denom = 1.0 — the old
    direct first-hop assignment), and hops run in the same j = 0..n-1
    order. Dropout stays chunk-invariant: the per-hop ctx hashes the
    GLOBAL (q, k) origin computed from the scanned hop index."""
    BH, T, D = q.shape
    ks = jnp.moveaxis(k.reshape(BH, n, c, D), 1, 0)       # [n, BH, c, D]
    vs = jnp.moveaxis(v.reshape(BH, n, c, D), 1, 0)
    kms = (None if km is None
           else jnp.moveaxis(km.reshape(BH, 1, n, c), 2, 0))
    js = jnp.arange(n, dtype=jnp.int32)
    outs, lses = [], []
    for i in range(n):
        qi = q[:, i * c:(i + 1) * c]

        def hop(carry, xs, qi=qi, i=i):
            o, lse = carry
            if dropout:
                kj, vj, kmj, j = xs
                ctx = _drop_ctx(seed, q_origin + i * c, k_origin + j * c)
                o_hop, lse_hop = flash_attention_lse_drop(
                    qi, kj, vj, kmj, ctx, sm_scale, False,
                    float(dropout), hash_t)
            elif km is None:
                kj, vj = xs
                o_hop, lse_hop = flash_attention_lse(qi, kj, vj,
                                                     sm_scale, False)
            else:
                kj, vj, kmj = xs
                o_hop, lse_hop = flash_attention_lse_masked(
                    qi, kj, vj, kmj, sm_scale, False)
            return lse_combine(o, lse, o_hop, lse_hop), None

        if dropout:
            xs = (ks, vs, kms, js)
        elif km is None:
            xs = (ks, vs)
        else:
            xs = (ks, vs, kms)
        carry0 = (jnp.zeros((BH, c, D), jnp.float32),
                  jnp.full((BH, c), NEG_INF, jnp.float32))
        (o, lse), _ = jax.lax.scan(hop, carry0, xs)
        outs.append(o.astype(q.dtype))
        lses.append(lse)
    return jnp.concatenate(outs, axis=1), jnp.concatenate(lses, axis=1)


def _broadcast_kmask(mask, B, H, T):
    """[B, T] key padding mask -> the kernels' [B*H, 1, T] operand (the
    singleton row dim satisfies Mosaic's (8,128)-divisible-or-equal block
    rule). The single home for this layout — flash_attention's masked and
    dropout branches and the chunk loop all build it here."""
    return jnp.broadcast_to(
        jnp.asarray(mask, jnp.float32)[:, None, :], (B, H, T)
    ).reshape(B * H, 1, T)


def flash_attention(q, k, v, *, causal=True, sm_scale=None, mask=None,
                    dropout=0.0, dropout_rng=None):
    """q, k, v: [B, H, T, D] -> [B, H, T, D]; differentiable (custom VJP).

    mask: optional [B, T] padding mask keyed on KEYS (1 = valid), the
    dense path's semantics (nn/layers/attention.dot_product_attention) —
    masked keys contribute no probability mass and receive zero dk/dv.
    dropout: attention-weight dropout rate, generated INSIDE the kernels
    from `dropout_rng` (a jax PRNG key) via the counter-based hash — the
    [B, H, T, T] mask never materializes in HBM."""
    B, H, T, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    if dropout:
        if dropout_rng is None:
            raise ValueError("dropout > 0 requires dropout_rng")
        ctx = _drop_ctx(_step_seed(dropout_rng))
        kmask = (jnp.ones((B * H, 1, T), jnp.float32) if mask is None
                 else _broadcast_kmask(mask, B, H, T))
        o = _flash_core_drop(qf, kf, vf, kmask, ctx, sm_scale,
                             bool(causal), float(dropout))
    elif mask is None:
        o = _flash_core(qf, kf, vf, sm_scale, bool(causal))
    else:
        kmask = _broadcast_kmask(mask, B, H, T)
        o = _flash_core_masked(qf, kf, vf, kmask, sm_scale, bool(causal))
    return o.reshape(B, H, T, D)
