"""Fused LayerNorm via Pallas — available but NOT the default.

One read + one write per pass: the forward saves per-row (mu, rstd), the
backward emits dx plus per-block dgamma/dbeta partials that sum outside.

Measured result (v5e, same-window A/B at the r4 flagship shapes — 6
blocks, d_model 256, seq 512): the fused kernel LOSES to XLA's native
lowering, 0.455 vs 0.494 MFU. XLA fuses the normalize chain INTO the
neighboring residual adds and matmul prologues; a pallas_call is a
fusion barrier, so the kernel's saved LN-local traffic is outweighed by
the materialization it forces around itself. `nn/layers/attention.
LayerNormImpl` therefore keeps the jnp form; this op remains for
compositions where LN has no fusable neighbors (e.g. standalone
normalization passes) and as the measured record of the experiment.

Envelope: feature dim C a lane-tile multiple (C % 128 == 0) and a
lane-legal row block. Interpret mode runs the same kernels on CPU for
the unit tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops import autotune

# row-block cap: resolved per (N, C) config through the tuning layer
# (ops/autotune.py); this name remains for the measured-default record
_ROW_BLOCK = autotune.DEFAULT_LN_ROW_BLOCK


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_rows(N: int, C: int) -> int:
    """Row block via the tuning layer: a valid table entry (TPU only)
    wins, else the power-of-two divisor search up to the swept cap.
    autotune.ln_rows enforces the stat-row legality rule on tuned
    values, so fwd and bwd always agree on bn."""
    return autotune.ln_rows(N, C)


def supports(shape, dtype=None) -> bool:
    if len(shape) < 2:
        return False
    C = shape[-1]
    N = int(np.prod(shape[:-1]))
    if C % 128 == 0 and N % 8 == 0:
        bn = _pick_rows(N, C)
        # the [1, N] stat rows use (1, bn) blocks: legal only when bn is
        # a lane-tile multiple or the whole row dim
        return bn % 128 == 0 or bn == N
    return False


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mu_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)                     # [bn, C]
    mu = jnp.mean(x, axis=1)
    xc = x - mu[:, None]
    var = jnp.mean(xc * xc, axis=1)
    rstd = jax.lax.rsqrt(var + eps)
    g = g_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    y = xc * rstd[:, None] * g[None] + b[None]
    y_ref[...] = y.astype(y_ref.dtype)
    mu_ref[...] = mu.reshape(mu_ref.shape)
    rstd_ref[...] = rstd.reshape(rstd_ref.shape)


def _bwd_kernel(x_ref, g_ref, mu_ref, rstd_ref, dy_ref, dx_ref, dg_ref,
                db_ref):
    x = x_ref[...].astype(jnp.float32)                     # [bn, C]
    dy = dy_ref[...].astype(jnp.float32)
    bn = x.shape[0]
    mu = mu_ref[...].reshape(bn)
    rstd = rstd_ref[...].reshape(bn)
    xn = (x - mu[:, None]) * rstd[:, None]
    wdy = dy * g_ref[...].astype(jnp.float32)[None]
    m1 = jnp.mean(wdy, axis=1)
    m2 = jnp.mean(wdy * xn, axis=1)
    dx = rstd[:, None] * (wdy - m1[:, None] - xn * m2[:, None])
    dx_ref[...] = dx.astype(dx_ref.dtype)
    dg_ref[...] = jnp.sum(dy * xn, axis=0).reshape(dg_ref.shape)
    db_ref[...] = jnp.sum(dy, axis=0).reshape(db_ref.shape)


def _ln_fwd(x2d, gamma, beta, eps):
    N, C = x2d.shape
    bn = _pick_rows(N, C)
    grid = (N // bn,)
    y, mu, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, C), lambda i: (i, 0)),
            pl.BlockSpec((C,), lambda i: (0,)),
            pl.BlockSpec((C,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((bn, C), lambda i: (i, 0)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, C), x2d.dtype),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(x2d, gamma, beta)
    return y, mu, rstd


def _ln_bwd(x2d, gamma, mu, rstd, dy):
    N, C = x2d.shape
    bn = _pick_rows(N, C)
    grid = (N // bn,)
    dx, dgp, dbp = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, C), lambda i: (i, 0)),
            pl.BlockSpec((C,), lambda i: (0,)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
            pl.BlockSpec((bn, C), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, C), lambda i: (i, 0)),
            # [nb, 1, C] partials: a (1, C) block over [nb, C] violates
            # the Mosaic (8,128)-or-full rule on the second-minor dim;
            # the singleton middle dim makes the last two dims (1, C) =
            # full-array (the same trick as the flash lse rows)
            pl.BlockSpec((1, 1, C), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, C), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, C), x2d.dtype),
            jax.ShapeDtypeStruct((N // bn, 1, C), jnp.float32),
            jax.ShapeDtypeStruct((N // bn, 1, C), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(x2d, gamma, mu, rstd, dy)
    return dx, dgp[:, 0].sum(0), dbp[:, 0].sum(0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x, gamma, beta, eps=1e-5):
    """LayerNorm over the LAST axis of x (any leading shape), fused.
    Returns y with x's dtype; statistics and normalization math in f32."""
    shape = x.shape
    y, _, _ = _ln_fwd(x.reshape(-1, shape[-1]), gamma, beta, eps)
    return y.reshape(shape)


def _fln_fwd(x, gamma, beta, eps):
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    y, mu, rstd = _ln_fwd(x2d, gamma, beta, eps)
    return y.reshape(shape), (x2d, gamma, mu, rstd, shape)


def _fln_bwd(eps, res, dy):
    x2d, gamma, mu, rstd, shape = res
    dx, dg, db = _ln_bwd(x2d, gamma, mu, rstd,
                         dy.reshape(-1, shape[-1]))
    return (dx.reshape(shape), dg.astype(gamma.dtype),
            db.astype(gamma.dtype))


fused_layer_norm.defvjp(_fln_fwd, _fln_bwd)
