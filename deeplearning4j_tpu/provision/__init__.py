"""Cluster provisioning (reference deeplearning4j-aws → TPU-VM)."""

from deeplearning4j_tpu.provision.tpu_vm import (  # noqa: F401
    TpuPodLauncher,
    TpuVmCreator,
    bootstrap_script,
)
