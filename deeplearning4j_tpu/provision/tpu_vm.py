"""TPU-VM provisioning — the reference's AWS module mapped to Cloud TPU.

Reference: `deeplearning4j-aws/.../Ec2BoxCreator.java` (create/blockUntil
running/terminate EC2 boxes) and `provision/install-deps.sh`-style
bootstrap. The TPU equivalent provisions TPU-VM pod slices: this module
generates the exact `gcloud compute tpus tpu-vm ...` invocations, the
per-host bootstrap script, and the multi-host launch plan wired to
`parallel.cluster.initialize_multihost` (jax.distributed). It builds
COMMANDS and SCRIPTS rather than calling cloud APIs directly — the
environment has no egress and no cloud credentials, and emitting the plan
keeps it auditable and dry-runnable (`--dry-run` prints what would run).
"""

from __future__ import annotations

import base64
import shlex
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class TpuVmCreator:
    """Ec2BoxCreator equivalent: lifecycle commands for one TPU VM/slice.

    accelerator_type: e.g. 'v5litepod-8' (one host) or 'v5litepod-256'
    (multi-host pod slice). runtime_version: the TPU software image.
    """

    name: str
    zone: str = "us-central1-a"
    accelerator_type: str = "v5litepod-8"
    runtime_version: str = "v2-alpha-tpuv5-lite"
    project: Optional[str] = None
    preemptible: bool = False
    labels: dict = field(default_factory=dict)

    def _base(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm"]
        return cmd

    def _scope(self) -> List[str]:
        out = ["--zone", self.zone]
        if self.project:
            out += ["--project", self.project]
        return out

    # ------------------------------------------------------------ lifecycle
    def create_command(self) -> List[str]:
        cmd = self._base() + ["create", self.name] + self._scope() + [
            "--accelerator-type", self.accelerator_type,
            "--version", self.runtime_version,
        ]
        if self.preemptible:
            cmd.append("--preemptible")
        if self.labels:
            cmd += ["--labels",
                    ",".join(f"{k}={v}" for k, v in sorted(self.labels.items()))]
        return cmd

    def delete_command(self) -> List[str]:
        return self._base() + ["delete", self.name, "--quiet"] + self._scope()

    def describe_command(self) -> List[str]:
        return self._base() + ["describe", self.name] + self._scope()

    def ssh_command(self, remote_command: str,
                    worker: str = "all") -> List[str]:
        return self._base() + ["ssh", self.name] + self._scope() + [
            "--worker", worker, "--command", remote_command]

    def scp_command(self, local_path: str, remote_path: str,
                    worker: str = "all") -> List[str]:
        return self._base() + ["scp", local_path,
                               f"{self.name}:{remote_path}"] + self._scope() + [
            "--worker", worker]

    def num_hosts(self) -> int:
        """Hosts in the slice. The accelerator-type suffix counts
        TensorCORES for v2/v3/v4/v5p (2 cores/chip x 4 chips = 8 per host)
        and CHIPS for the 'lite' types v5e/v6e (8 single-core chips per
        host) — either way the divisor is 8."""
        n = int(self.accelerator_type.rsplit("-", 1)[1])
        return max(1, n // 8)


def bootstrap_script(package_source: str = "deeplearning4j_tpu",
                     extra_env: Optional[dict] = None) -> str:
    """Per-host bootstrap (the reference's provisioning shell): install the
    framework and leave a marker. jax[tpu] ships preinstalled on TPU-VM
    runtime images, so only the framework itself is installed."""
    env_lines = "\n".join(
        f"echo 'export {k}={shlex.quote(str(v))}' >> ~/.profile"
        for k, v in (extra_env or {}).items())
    return f"""#!/usr/bin/env bash
set -euo pipefail
python3 -m pip install --upgrade pip
python3 -m pip install {shlex.quote(package_source)}
{env_lines}
python3 -c "import deeplearning4j_tpu, jax; print('ok', jax.device_count())"
touch ~/.deeplearning4j_tpu_provisioned
"""


class TpuPodLauncher:
    """Multi-host launch plan: bootstrap every host, then start the same
    training entrypoint on each with jax.distributed coordinates (the
    reference's master/worker actor bootstrap, minus Akka).

    Process 0's host doubles as the jax.distributed coordinator; the
    training entrypoint calls `parallel.cluster.initialize_multihost`
    with the env vars this launcher sets.
    """

    def __init__(self, creator: TpuVmCreator):
        self.creator = creator

    def launch_commands(self, train_command: str) -> List[List[str]]:
        """One broadcast ssh (`--worker=all`) running the training
        entrypoint on every host. On Cloud TPU pod slices
        `jax.distributed.initialize()` (and thus
        `parallel.cluster.initialize_multihost()` with no arguments)
        auto-detects coordinator address, process count, and process id
        from the TPU metadata server — no per-host environment wiring is
        needed or attempted here."""
        n = self.creator.num_hosts()
        remote = f"DL4J_TPU_EXPECTED_HOSTS={n} {train_command}"
        return [self.creator.ssh_command(remote, worker="all")]

    def plan(self, train_command: str,
             package_source: str = "deeplearning4j_tpu") -> List[str]:
        """Full ordered dry-run plan as printable shell lines."""
        script = bootstrap_script(package_source)
        # ship the multiline script intact: base64 through the ssh command
        # (newline-folding would hide everything behind the shebang comment)
        encoded = base64.b64encode(script.encode()).decode()
        steps = [self.creator.create_command()]
        steps.append(self.creator.ssh_command(
            f"echo {encoded} | base64 -d | bash", worker="all"))
        steps += self.launch_commands(train_command)
        return [" ".join(shlex.quote(part) for part in cmd) for cmd in steps]
