"""TPU-VM provisioning — the reference's AWS module mapped to Cloud TPU.

Reference: `deeplearning4j-aws/.../Ec2BoxCreator.java` (create/blockUntil
running/terminate EC2 boxes) and `provision/install-deps.sh`-style
bootstrap. The TPU equivalent provisions TPU-VM pod slices: this module
generates the exact `gcloud compute tpus tpu-vm ...` invocations, the
per-host bootstrap script, and the multi-host launch plan wired to
`distributed.bootstrap.initialize` (jax.distributed) — either via TPU
metadata auto-detection or via `pod_launch_script`'s explicit env
contract, the same one the off-TPU test fleet uses. It builds
COMMANDS and SCRIPTS rather than calling cloud APIs directly — the
environment has no egress and no cloud credentials, and emitting the plan
keeps it auditable and dry-runnable (`--dry-run` prints what would run).
"""

from __future__ import annotations

import base64
import shlex
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class TpuVmCreator:
    """Ec2BoxCreator equivalent: lifecycle commands for one TPU VM/slice.

    accelerator_type: e.g. 'v5litepod-8' (one host) or 'v5litepod-256'
    (multi-host pod slice). runtime_version: the TPU software image.
    """

    name: str
    zone: str = "us-central1-a"
    accelerator_type: str = "v5litepod-8"
    runtime_version: str = "v2-alpha-tpuv5-lite"
    project: Optional[str] = None
    preemptible: bool = False
    labels: dict = field(default_factory=dict)

    def _base(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm"]
        return cmd

    def _scope(self) -> List[str]:
        out = ["--zone", self.zone]
        if self.project:
            out += ["--project", self.project]
        return out

    # ------------------------------------------------------------ lifecycle
    def create_command(self) -> List[str]:
        cmd = self._base() + ["create", self.name] + self._scope() + [
            "--accelerator-type", self.accelerator_type,
            "--version", self.runtime_version,
        ]
        if self.preemptible:
            cmd.append("--preemptible")
        if self.labels:
            cmd += ["--labels",
                    ",".join(f"{k}={v}" for k, v in sorted(self.labels.items()))]
        return cmd

    def delete_command(self) -> List[str]:
        return self._base() + ["delete", self.name, "--quiet"] + self._scope()

    def describe_command(self) -> List[str]:
        return self._base() + ["describe", self.name] + self._scope()

    def ssh_command(self, remote_command: str,
                    worker: str = "all") -> List[str]:
        return self._base() + ["ssh", self.name] + self._scope() + [
            "--worker", worker, "--command", remote_command]

    def scp_command(self, local_path: str, remote_path: str,
                    worker: str = "all") -> List[str]:
        return self._base() + ["scp", local_path,
                               f"{self.name}:{remote_path}"] + self._scope() + [
            "--worker", worker]

    def num_hosts(self) -> int:
        """Hosts in the slice. The accelerator-type suffix counts
        TensorCORES for v2/v3/v4/v5p (2 cores/chip x 4 chips = 8 per host)
        and CHIPS for the 'lite' types v5e/v6e (8 single-core chips per
        host) — either way the divisor is 8."""
        n = int(self.accelerator_type.rsplit("-", 1)[1])
        return max(1, n // 8)


def bootstrap_script(package_source: str = "deeplearning4j_tpu",
                     extra_env: Optional[dict] = None) -> str:
    """Per-host bootstrap (the reference's provisioning shell): install the
    framework and leave a marker. jax[tpu] ships preinstalled on TPU-VM
    runtime images, so only the framework itself is installed."""
    env_lines = "\n".join(
        f"echo 'export {k}={shlex.quote(str(v))}' >> ~/.profile"
        for k, v in (extra_env or {}).items())
    return f"""#!/usr/bin/env bash
set -euo pipefail
python3 -m pip install --upgrade pip
python3 -m pip install {shlex.quote(package_source)}
{env_lines}
python3 -c "import deeplearning4j_tpu, jax; print('ok', jax.device_count())"
touch ~/.deeplearning4j_tpu_provisioned
"""


def pod_launch_script(train_command: str, num_hosts: int,
                      coordinator_port: int = 8476) -> str:
    """Pod-ready launch script for EVERY host of a slice, driving the
    `distributed/bootstrap.py` env contract on real TPU hardware.

    Cloud TPU runtime images export ``TPU_WORKER_ID`` (this host's index)
    and ``TPU_WORKER_HOSTNAMES`` (comma list, host 0 first) on each VM;
    the script translates them into the same DL4J_TPU_* contract the
    local launcher wires, with host 0 as the jax.distributed coordinator.
    `bootstrap.initialize()` inside the training entrypoint then behaves
    identically on a pod and in an off-TPU simulated fleet — one
    rendezvous code path, exercised by the CPU tests, launched here.
    """
    from deeplearning4j_tpu.distributed import bootstrap as _bootstrap

    return f"""#!/usr/bin/env bash
set -euo pipefail
# rendezvous env contract (deeplearning4j_tpu/distributed/bootstrap.py):
# host 0 of the slice hosts the jax.distributed coordination service
WORKER_ID="${{TPU_WORKER_ID:-0}}"
HOSTS="${{TPU_WORKER_HOSTNAMES:-127.0.0.1}}"
COORD_HOST="${{HOSTS%%,*}}"
export {_bootstrap.ENV_PROCESS_ID}="$WORKER_ID"
export {_bootstrap.ENV_NUM_PROCESSES}={num_hosts}
export {_bootstrap.ENV_COORDINATOR}="$COORD_HOST:{coordinator_port}"
exec {train_command}
"""


class TpuPodLauncher:
    """Multi-host launch plan: bootstrap every host, then start the same
    training entrypoint on each with jax.distributed coordinates (the
    reference's master/worker actor bootstrap, minus Akka).

    Process 0's host doubles as the jax.distributed coordinator; the
    training entrypoint calls `distributed.bootstrap.initialize()` (or
    the `parallel.cluster.initialize_multihost` alias), fed either by
    TPU-metadata auto-detection or by the explicit env contract of
    `pod_launch_script`.
    """

    def __init__(self, creator: TpuVmCreator):
        self.creator = creator

    def launch_commands(self, train_command: str) -> List[List[str]]:
        """One broadcast ssh (`--worker=all`) running the training
        entrypoint on every host. On Cloud TPU pod slices
        `jax.distributed.initialize()` (and thus
        `distributed.bootstrap.initialize()` with no arguments)
        auto-detects coordinator address, process count, and process id
        from the TPU metadata server — no per-host environment wiring is
        needed or attempted here."""
        n = self.creator.num_hosts()
        remote = f"DL4J_TPU_EXPECTED_HOSTS={n} {train_command}"
        return [self.creator.ssh_command(remote, worker="all")]

    def pod_launch_commands(self, train_command: str,
                            coordinator_port: int = 8476) -> List[List[str]]:
        """Broadcast launch through `pod_launch_script`: every host runs
        the same script, which derives its process id / coordinator from
        the TPU runtime env and exports the explicit DL4J_TPU_* contract
        before exec'ing the entrypoint. Use this instead of
        `launch_commands` when the rendezvous must be explicit (mixed
        runtime versions, DCN multi-slice, or debugging a wedged
        auto-detection)."""
        script = pod_launch_script(train_command, self.creator.num_hosts(),
                                   coordinator_port)
        encoded = base64.b64encode(script.encode()).decode()
        return [self.creator.ssh_command(
            f"echo {encoded} | base64 -d | bash", worker="all")]

    def plan(self, train_command: str,
             package_source: str = "deeplearning4j_tpu",
             explicit_rendezvous: bool = False) -> List[str]:
        """Full ordered dry-run plan as printable shell lines.
        explicit_rendezvous=True launches through `pod_launch_script`'s
        env contract instead of TPU-metadata auto-detection."""
        script = bootstrap_script(package_source)
        # ship the multiline script intact: base64 through the ssh command
        # (newline-folding would hide everything behind the shebang comment)
        encoded = base64.b64encode(script.encode()).decode()
        steps = [self.creator.create_command()]
        steps.append(self.creator.ssh_command(
            f"echo {encoded} | base64 -d | bash", worker="all"))
        if explicit_rendezvous:
            steps += self.pod_launch_commands(train_command)
        else:
            steps += self.launch_commands(train_command)
        return [" ".join(shlex.quote(part) for part in cmd) for cmd in steps]
