"""Cloud-storage data I/O — the reference's S3 module mapped to GCS.

Reference: `deeplearning4j-aws/.../s3/{S3Downloader,S3Uploader,
BaseS3DataSetIterator}` (stream datasets from buckets into the training
loop). TPU-side storage is GCS; this module shells out to `gcloud storage`
(falling back to `gsutil`) for transfers, keeps a local cache directory,
and iterates serialized DataSets (.npz) from a bucket prefix. Every code
path also accepts plain local directories, so the pipeline is fully
testable offline (zero egress) and local paths double as a filesystem
"bucket" for development.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

_CACHE = os.path.expanduser("~/.cache/deeplearning4j_tpu/gcs")


def _is_remote(path: str) -> bool:
    return path.startswith("gs://")


def _cli() -> Optional[List[str]]:
    if shutil.which("gcloud"):
        return ["gcloud", "storage"]
    if shutil.which("gsutil"):
        return ["gsutil"]
    return None


class GcsDownloader:
    """S3Downloader equivalent: fetch objects to a local cache."""

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir or _CACHE

    def download(self, uri: str, dest: Optional[str] = None) -> str:
        if not _is_remote(uri):
            return uri  # local path passthrough
        # preserve the object path hierarchy: flattening '/' would collide
        # distinct objects onto one cache file
        dest = dest or os.path.join(self.cache_dir, uri[len("gs://"):])
        if os.path.exists(dest):
            return dest
        cli = _cli()
        if cli is None:
            raise RuntimeError(
                "no gcloud/gsutil on PATH — install the Cloud SDK or pass "
                "a local path")
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        # download to a temp name and rename: a transfer killed mid-way
        # must not leave a truncated file that reads as a cache hit forever
        tmp = dest + f".tmp{os.getpid()}"
        try:
            subprocess.run(cli + ["cp", uri, tmp], check=True,
                           capture_output=True)
            os.replace(tmp, dest)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return dest

    def list(self, prefix: str) -> List[str]:
        if not _is_remote(prefix):
            return sorted(
                os.path.join(prefix, f) for f in os.listdir(prefix)
                if os.path.isfile(os.path.join(prefix, f)))
        cli = _cli()
        if cli is None:
            raise RuntimeError("no gcloud/gsutil on PATH")
        out = subprocess.run(cli + ["ls", prefix], check=True,
                             capture_output=True, text=True)
        return [l.strip() for l in out.stdout.splitlines() if l.strip()]


class GcsUploader:
    """S3Uploader equivalent."""

    def upload(self, local_path: str, uri: str) -> None:
        if not _is_remote(uri):
            os.makedirs(os.path.dirname(uri) or ".", exist_ok=True)
            shutil.copyfile(local_path, uri)
            return
        cli = _cli()
        if cli is None:
            raise RuntimeError("no gcloud/gsutil on PATH")
        subprocess.run(cli + ["cp", local_path, uri], check=True,
                       capture_output=True)


def save_dataset(ds: DataSet, path: str) -> None:
    """Serialize one DataSet as .npz (the S3 object format here)."""
    arrs = {"features": ds.features, "labels": ds.labels}
    if ds.features_mask is not None:
        arrs["features_mask"] = ds.features_mask
    if ds.labels_mask is not None:
        arrs["labels_mask"] = ds.labels_mask
    np.savez_compressed(path, **arrs)


def load_dataset(path: str) -> DataSet:
    with np.load(path) as z:
        return DataSet(z["features"], z["labels"],
                       z["features_mask"] if "features_mask" in z else None,
                       z["labels_mask"] if "labels_mask" in z else None)


class GcsDataSetIterator(DataSetIterator):
    """BaseS3DataSetIterator equivalent: iterate .npz DataSets under a
    bucket prefix (or local directory), downloading through the cache."""

    def __init__(self, prefix: str, cache_dir: Optional[str] = None):
        super().__init__()
        self.downloader = GcsDownloader(cache_dir)
        self.uris = [u for u in self.downloader.list(prefix)
                     if u.endswith(".npz")]
        if not self.uris:
            raise IOError(f"no .npz datasets under {prefix}")
        self._i = 0

    def has_next(self) -> bool:
        return self._i < len(self.uris)

    def next(self, num=None) -> DataSet:
        if not self.has_next():
            raise StopIteration
        uri = self.uris[self._i]
        self._i += 1
        return self._apply_pre(load_dataset(self.downloader.download(uri)))

    def reset(self) -> None:
        self._i = 0

    def batch(self) -> int:
        return -1

    def total_examples(self) -> int:
        return -1
