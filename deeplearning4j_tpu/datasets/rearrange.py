"""Unstructured-data train/test splitter (reference
datasets/rearrange/LocalUnstructuredDataFormatter.java).

Takes a directory tree of raw example files and rearranges it into

    <dest>/split/train/<label>/<file>
    <dest>/split/test/<label>/<file>

with the label taken either from each file's parent directory name
(LabelingType.DIRECTORY) or parsed out of the file name's trailing
"-<label>.<ext>" segment (LabelingType.NAME — reference getNameLabel
scans back from the extension to the last dash). Files are shuffled
before the split so train/test are random samples.
"""

from __future__ import annotations

import enum
import os
import random
import shutil
from typing import List, Optional


class LabelingType(enum.Enum):
    NAME = "name"
    DIRECTORY = "directory"


class LocalUnstructuredDataFormatter:
    def __init__(self, destination_root_dir: str, root_dir: str,
                 labeling_type: LabelingType = LabelingType.DIRECTORY,
                 percent_train: float = 0.8,
                 seed: Optional[int] = None):
        self.root_dir = root_dir
        self.split_root = os.path.join(destination_root_dir, "split")
        if os.path.exists(self.split_root):
            raise FileExistsError("Train/test split already exists")
        self.train_dir = os.path.join(self.split_root, "train")
        self.test_dir = os.path.join(self.split_root, "test")
        os.makedirs(self.train_dir)
        os.makedirs(self.test_dir)
        self.labeling_type = labeling_type
        self.percent_train = percent_train
        self.seed = seed
        self.num_examples_total = -1
        self.num_examples_to_train_on = -1
        self.num_test_examples = -1

    def rearrange(self) -> None:
        all_files: List[str] = []
        for base, _dirs, names in os.walk(self.root_dir):
            for n in names:
                all_files.append(os.path.join(base, n))
        self.num_examples_total = len(all_files)
        n_train = int(self.percent_train * self.num_examples_total)
        self.num_examples_to_train_on = n_train
        self.num_test_examples = self.num_examples_total - n_train
        random.Random(self.seed).shuffle(all_files)
        ok = False
        try:
            # validate every label BEFORE copying so a bad file name can't
            # leave a partial split behind (which would then block reruns
            # with FileExistsError)
            dests = [self.get_new_destination(p, train=i < n_train)
                     for i, p in enumerate(all_files)]
            for i, (path, dest) in enumerate(zip(all_files, dests)):
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                if os.path.exists(dest):
                    # same basename under the same label from different
                    # source dirs: disambiguate, don't silently overwrite
                    d, name = os.path.split(dest)
                    dest = os.path.join(d, f"{i}-{name}")
                shutil.copy(path, dest)
            ok = True
        finally:
            # finally (not except) so Ctrl-C mid-copy also cleans up
            if not ok:
                shutil.rmtree(self.split_root, ignore_errors=True)

    def get_new_destination(self, path: str, train: bool) -> str:
        base = self.train_dir if train else self.test_dir
        if self.labeling_type is LabelingType.DIRECTORY:
            label = self.get_path_label(path)
        else:
            label = self.get_name_label(path)
        return os.path.join(base, label, os.path.basename(path))

    @staticmethod
    def get_path_label(path: str) -> str:
        return os.path.basename(os.path.dirname(path))

    @staticmethod
    def get_name_label(path: str) -> str:
        """Label embedded in the file name as ...-<label>.<ext>."""
        name = os.path.basename(path)
        stem, dot, _ext = name.rpartition(".")
        if not dot:
            raise ValueError(f"Illegal path; no format found: {path}")
        _prefix, dash, label = stem.rpartition("-")
        if not dash:
            raise ValueError(
                f"Illegal path; no dash found (a dash marks the label): "
                f"{path}")
        return label

    # ----------------------------------------------------------- accessors
    def get_train(self) -> str:
        return self.train_dir

    def get_test(self) -> str:
        return self.test_dir
