"""Curves dataset fetcher (reference `fetchers/CurvesDataFetcher.java`).

The reference downloads a serialized `curves.ser` blob of 28x28 synthetic
curve images (the deep-autoencoder pretraining benchmark from
Hinton/Salakhutdinov). Zero-egress here: the same kind of data — smooth
random curves rasterized onto a 28x28 grid — is synthesized
deterministically. The fetcher API matches MnistDataFetcher (features as
flat rows in [0,1]; curves have no labels, the dataset is its own target,
matching the reference where fetch() sets labels = features for the
autoencoder use case).
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator

IMAGE_SIZE = 28


def _rasterize_curve(rng: np.random.Generator, size: int) -> np.ndarray:
    """Draw one smooth random curve (cubic Bezier) with soft strokes."""
    p = rng.random((4, 2)) * (size - 1)
    t = np.linspace(0.0, 1.0, 6 * size)[:, None]
    b = ((1 - t) ** 3 * p[0] + 3 * (1 - t) ** 2 * t * p[1]
         + 3 * (1 - t) * t ** 2 * p[2] + t ** 3 * p[3])
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    # soft gaussian stroke around sampled curve points (vectorized)
    d2 = ((yy[None] - b[:, 1, None, None]) ** 2
          + (xx[None] - b[:, 0, None, None]) ** 2)
    img = np.exp(-d2 / 1.2).max(axis=0)
    return img


class CurvesDataFetcher:
    """Synthesizes the full curves split into memory once."""

    def __init__(self, num_examples: int = 2000, seed: int = 123):
        rng = np.random.default_rng(seed)
        imgs = np.stack([_rasterize_curve(rng, IMAGE_SIZE)
                         for _ in range(num_examples)])
        self.features = imgs.reshape(num_examples, -1).astype(np.float32)

    def fetch(self, num: int) -> DataSet:
        """Reference fetch(): labels == features (autoencoder target)."""
        x = self.features[:num]
        return DataSet(x, x.copy())


class CurvesDataSetIterator(ArrayDataSetIterator):
    """Batched iterator over the curves set (features double as labels)."""

    def __init__(self, batch_size: int, num_examples: int = 2000,
                 seed: int = 123):
        f = CurvesDataFetcher(num_examples=num_examples, seed=seed)
        super().__init__(f.features, f.features.copy(), batch_size)
