"""DataSet containers (reference: external ND4J DataSet/MultiDataSet,
consumed throughout deeplearning4j-core).

A DataSet is host-side numpy (features, labels, optional masks); device
transfer happens inside the jitted step. Masks follow the reference's
variable-length time-series semantics ([batch, time] of 0/1).
"""

from __future__ import annotations

import numpy as np


class DataSet:
    def __init__(self, features, labels=None, features_mask=None, labels_mask=None):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels) if labels is not None else None
        self.features_mask = None if features_mask is None else np.asarray(features_mask)
        self.labels_mask = None if labels_mask is None else np.asarray(labels_mask)

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, n_train: int):
        tr = DataSet(self.features[:n_train], self.labels[:n_train],
                     None if self.features_mask is None else self.features_mask[:n_train],
                     None if self.labels_mask is None else self.labels_mask[:n_train])
        te = DataSet(self.features[n_train:], self.labels[n_train:],
                     None if self.features_mask is None else self.features_mask[n_train:],
                     None if self.labels_mask is None else self.labels_mask[n_train:])
        return tr, te

    def shuffle(self, seed=None):
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        self.features = self.features[idx]
        if self.labels is not None:
            self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]

    def batch_by(self, n: int):
        out = []
        for i in range(0, self.num_examples(), n):
            out.append(DataSet(
                self.features[i:i + n],
                None if self.labels is None else self.labels[i:i + n],
                None if self.features_mask is None else self.features_mask[i:i + n],
                None if self.labels_mask is None else self.labels_mask[i:i + n],
            ))
        return out

    @staticmethod
    def merge(datasets):
        f = np.concatenate([d.features for d in datasets])
        l = (np.concatenate([d.labels for d in datasets])
             if datasets[0].labels is not None else None)
        return DataSet(f, l)

    def scale_min_max(self, lo=0.0, hi=1.0):
        mn, mx = self.features.min(), self.features.max()
        self.features = (self.features - mn) / max(mx - mn, 1e-12) * (hi - lo) + lo

    def normalize_zero_mean_unit_variance(self):
        mu = self.features.mean(axis=0)
        sd = self.features.std(axis=0) + 1e-12
        self.features = (self.features - mu) / sd


class MultiDataSet:
    """Multiple-input/output container (reference MultiDataSet for
    ComputationGraph)."""

    def __init__(self, features, labels, features_masks=None, labels_masks=None):
        self.features = [np.asarray(f) for f in features]
        self.labels = [np.asarray(l) for l in labels]
        self.features_masks = features_masks
        self.labels_masks = labels_masks

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])
