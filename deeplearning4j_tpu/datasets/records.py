"""Record readers — the host-side data-loading library replacing Canova.

Reference: external canova-api record readers (CSV, SVMLight, image) bridged
by datasets/canova/RecordReaderDataSetIterator.java:47,
SequenceRecordReaderDataSetIterator and RecordReaderMultiDataSetIterator.

Pure NumPy host-side parsing feeding device buffers (SURVEY.md §2.1 Canova
row: "host-side data loading library").
"""

from __future__ import annotations

import csv
import os

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


class RecordReader:
    """Iterates records (lists of values) from a source."""

    def __iter__(self):
        raise NotImplementedError

    def reset(self):
        pass


class CSVRecordReader(RecordReader):
    """CSV lines → float records (reference canova CSVRecordReader)."""

    def __init__(self, path: str, skip_lines: int = 0, delimiter: str = ","):
        self.path = path
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def to_matrix(self):
        """Whole file as a float32 matrix via the native IO core
        (deeplearning4j_tpu/native), or None when the file has
        non-numeric cells / no toolchain — callers then iterate records."""
        from deeplearning4j_tpu import native

        return native.load_csv(self.path, self.skip_lines, self.delimiter)

    def __iter__(self):
        with open(self.path, newline="") as f:
            r = csv.reader(f, delimiter=self.delimiter)
            for i, row in enumerate(r):
                if i < self.skip_lines or not row:
                    continue
                yield [v.strip() for v in row]


class SVMLightRecordReader(RecordReader):
    """SVMLight/LibSVM sparse format: `label idx:val idx:val ...`
    (reference canova SVMLightRecordReader; dl4j-test-resources/svmLight)."""

    def __init__(self, path: str, num_features: int):
        self.path = path
        self.num_features = num_features

    def to_arrays(self):
        """(labels, dense features) via the native IO core, or None."""
        from deeplearning4j_tpu import native

        return native.load_svmlight(self.path, self.num_features)

    def __iter__(self):
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split()
                label = float(parts[0])
                feats = np.zeros(self.num_features, np.float32)
                for tok in parts[1:]:
                    if ":" in tok:
                        i, v = tok.split(":")
                        feats[int(i) - 1] = float(v)
                yield label, feats


class ListStringRecordReader(RecordReader):
    def __init__(self, rows):
        self.rows = rows

    def __iter__(self):
        return iter(self.rows)


class RecordReaderDataSetIterator(DataSetIterator):
    """records → minibatched DataSets (reference
    datasets/canova/RecordReaderDataSetIterator.java:47). label_index
    selects the class column; num_classes one-hot encodes it; regression
    keeps the raw value."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: int = -1,
                 regression: bool = False):
        super().__init__()
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self._it = None
        self._done = False
        self._pending = None
        self.reset()

    def reset(self):
        self.reader.reset()
        self._matrix = self._try_native()
        self._mat_pos = 0
        self._it = iter(self.reader) if self._matrix is None else None
        self._done = False
        self._pending = None

    def _try_native(self):
        """Vectorized whole-file path (native IO core) when the reader
        supports it; None falls back to per-record iteration."""
        if hasattr(self.reader, "to_matrix"):
            m = self.reader.to_matrix()
            if m is not None:
                li = self.label_index if self.label_index >= 0 else m.shape[1] - 1
                feats = np.delete(m, li, axis=1)
                return feats, m[:, li]
        if hasattr(self.reader, "to_arrays"):
            arrs = self.reader.to_arrays()
            if arrs is not None:
                labels, feats = arrs
                return feats, labels
        return None

    def _native_batch(self):
        feats, labels = self._matrix
        if self._mat_pos >= len(feats):
            self._done = True
            return None
        sl = slice(self._mat_pos, self._mat_pos + self.batch_size)
        self._mat_pos += self.batch_size
        x, l = feats[sl], labels[sl]
        if self.regression:
            y = np.asarray(l, np.float32)[:, None]
        elif self.num_classes > 0:
            y = np.eye(self.num_classes, dtype=np.float32)[
                np.asarray(l, np.int64)]
        else:
            y = np.asarray(l, np.float32)[:, None]
        return DataSet(np.ascontiguousarray(x), y)

    def _read_batch(self):
        if self._matrix is not None:
            return self._native_batch()
        feats, labels = [], []
        while len(feats) < self.batch_size:
            try:
                rec = next(self._it)
            except StopIteration:
                self._done = True
                break
            if isinstance(rec, tuple) and len(rec) == 2 and isinstance(
                    rec[1], np.ndarray):  # svmlight (label, features)
                label, f = rec
                feats.append(f)
                labels.append(label)
            else:
                vals = list(rec)
                li = self.label_index if self.label_index >= 0 else len(vals) - 1
                label = vals[li]
                f = [float(v) for j, v in enumerate(vals) if j != li]
                feats.append(np.asarray(f, np.float32))
                labels.append(label)
        if not feats:
            return None
        x = np.stack(feats)
        if self.regression:
            y = np.asarray([float(l) for l in labels], np.float32)[:, None]
        elif self.num_classes > 0:
            idx = np.asarray([int(float(l)) for l in labels])
            y = np.eye(self.num_classes, dtype=np.float32)[idx]
        else:
            y = np.asarray([float(l) for l in labels], np.float32)[:, None]
        return DataSet(x, y)

    def has_next(self):
        if self._pending is None and not self._done:
            self._pending = self._read_batch()
        return self._pending is not None

    def next(self, num=None):
        if not self.has_next():
            raise StopIteration
        ds, self._pending = self._pending, None
        return self._apply_pre(ds)

    def batch(self):
        return self.batch_size


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Per-sequence CSV files → padded+masked time-series DataSets
    (reference SequenceRecordReaderDataSetIterator). feature_dir and
    label_dir hold aligned files; variable lengths are padded and masked —
    the reference's variable-length masking path."""

    def __init__(self, sequences, labels, batch_size: int, num_classes: int = -1):
        """sequences: list of [T_i, F] arrays; labels: list of [T_i] int
        arrays (per-step classes) or scalars (per-sequence class)."""
        super().__init__()
        self.sequences = [np.asarray(s, np.float32) for s in sequences]
        self.labels = labels
        self.batch_size = batch_size
        self.num_classes = num_classes
        self._i = 0

    @staticmethod
    def from_csv_dirs(feature_dir, label_dir, batch_size, num_classes):
        seqs, labs = [], []
        for fname in sorted(os.listdir(feature_dir)):
            seqs.append(np.loadtxt(os.path.join(feature_dir, fname),
                                   delimiter=",", ndmin=2))
            labs.append(np.loadtxt(os.path.join(label_dir, fname),
                                   delimiter=",", ndmin=1))
        return SequenceRecordReaderDataSetIterator(seqs, labs, batch_size, num_classes)

    def has_next(self):
        return self._i < len(self.sequences)

    def next(self, num=None):
        n = num or self.batch_size
        seqs = self.sequences[self._i:self._i + n]
        labs = self.labels[self._i:self._i + n]
        self._i += n
        T = max(s.shape[0] for s in seqs)
        F = seqs[0].shape[1]
        B = len(seqs)
        x = np.zeros((B, T, F), np.float32)
        mask = np.zeros((B, T), np.float32)
        per_step = np.ndim(labs[0]) >= 1 and np.size(labs[0]) > 1
        if per_step:
            y = np.zeros((B, T, max(self.num_classes, 1)), np.float32)
        else:
            y = np.zeros((B, max(self.num_classes, 1)), np.float32)
        for b, (s, l) in enumerate(zip(seqs, labs)):
            t = s.shape[0]
            x[b, :t] = s
            mask[b, :t] = 1
            if per_step:
                idx = np.asarray(l, np.int64)[:t]
                y[b, np.arange(t), idx] = 1
            else:
                y[b, int(np.ravel(l)[0])] = 1
        return self._apply_pre(DataSet(x, y, features_mask=mask,
                                       labels_mask=mask if per_step else None))

    def reset(self):
        self._i = 0

    def batch(self):
        return self.batch_size


class RecordReaderMultiDataSetIterator(DataSetIterator):
    """Multiple readers → MultiDataSet (reference
    RecordReaderMultiDataSetIterator). Each named reader contributes inputs
    and/or outputs by column spec."""

    def __init__(self, batch_size: int):
        super().__init__()
        self.batch_size = batch_size
        self._inputs = []  # (reader, cols)
        self._outputs = []  # (reader, cols, num_classes)
        self._iters = None
        self._done = False
        self._pending = None

    def add_input(self, reader: RecordReader, cols=None):
        self._inputs.append((reader, cols))
        return self

    def add_output(self, reader: RecordReader, cols=None, num_classes: int = -1):
        self._outputs.append((reader, cols, num_classes))
        return self

    def reset(self):
        for r, *_ in self._inputs + self._outputs:
            r.reset()
        self._iters = ([iter(r) for r, _ in self._inputs],
                       [iter(r) for r, _, _ in self._outputs])
        self._done = False
        self._pending = None

    def _take(self, it, cols):
        rec = [float(v) for v in next(it)]
        if cols is not None:
            rec = [rec[c] for c in cols]
        return rec

    def _read_row(self):
        """Read one aligned row from ALL readers atomically: if any reader is
        exhausted the whole row is discarded (no misaligned partial rows)."""
        row_in, row_out = [], []
        try:
            for it, (_, cols) in zip(self._iters[0], self._inputs):
                row_in.append(self._take(it, cols))
            for it, (_, cols, _nc) in zip(self._iters[1], self._outputs):
                row_out.append(self._take(it, cols))
        except StopIteration:
            return None
        return row_in, row_out

    def _read_batch(self):
        in_rows = [[] for _ in self._inputs]
        out_rows = [[] for _ in self._outputs]
        count = 0
        while count < self.batch_size:
            row = self._read_row()
            if row is None:
                self._done = True
                break
            for j, r in enumerate(row[0]):
                in_rows[j].append(r)
            for j, r in enumerate(row[1]):
                out_rows[j].append(r)
            count += 1
        if count == 0:
            return None
        feats = [np.asarray(r, np.float32) for r in in_rows]
        labels = []
        for rows, (_, _, nc) in zip(out_rows, self._outputs):
            arr = np.asarray(rows, np.float32)
            if nc > 0:
                idx = arr.astype(np.int64).ravel()
                arr = np.eye(nc, dtype=np.float32)[idx]
            labels.append(arr)
        return MultiDataSet(feats, labels)

    def has_next(self):
        if self._iters is None:
            self.reset()
        if self._pending is None and not self._done:
            self._pending = self._read_batch()
        return self._pending is not None

    def next(self, num=None):
        if not self.has_next():
            raise StopIteration
        mds, self._pending = self._pending, None
        return mds

    def batch(self):
        return self.batch_size
