"""AsyncDataSetIterator — background host prefetch.

Reference: datasets/iterator/AsyncDataSetIterator.java:30-105 (producer
thread + blocking queue; MultiLayerNetwork.fit wraps every iterator in
one, MultiLayerNetwork.java:1014).

Since ISSUE 12 this is a THIN ADAPTER over the one background-prefetch
implementation in the tree (`data/prefetcher.Prefetcher`): the r6
hand-rolled queue had polling waits (`put(timeout=0.1)` /
`get(timeout=0.5)` spin loops that burned a core while idle) and a
shutdown hole — a producer dying after ``put_nowait(_SENTINEL)`` hit
``queue.Full`` left ``reset()``'s drain loop spinning forever. The
Channel underneath is event-driven (condition variables, no timeouts)
and signals EOS/error out-of-band, so neither failure mode exists.

The fit loops themselves now ride `data/pipeline.iter_prefetched`
(which also moves `_batch_dict` conversion and the device put off the
step thread); this class remains the public API for callers that want
plain host-side DataSet prefetch.
"""

from __future__ import annotations

from deeplearning4j_tpu.data.prefetcher import EOS, Prefetcher
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


class AsyncDataSetIterator(DataSetIterator):
    def __init__(self, underlying: DataSetIterator, queue_size: int = 8):
        super().__init__()
        self._under = underlying
        self._size = queue_size
        self._peek = None
        self._start()

    def _start(self):
        under = self._under

        def source():
            while under.has_next():
                yield under.next()

        self._peek = None
        self._pf = Prefetcher(source, depth=self._size,
                              name="async-dataset-iterator")

    def _fill_peek(self):
        if self._peek is None:
            # blocks event-driven; raises the producer's exception here,
            # on the consumer thread, if iteration failed
            self._peek = self._pf.get()

    def has_next(self):
        self._fill_peek()
        return self._peek is not EOS

    def next(self, num=None):
        self._fill_peek()
        if self._peek is EOS:
            raise StopIteration
        ds, self._peek = self._peek, None
        return self._apply_pre(ds)

    def reset(self):
        # stop() wakes a producer blocked on a full channel, discards
        # buffered items under the lock, and joins the thread — drain is
        # immune to any producer death mode (EOS, error, mid-put)
        self._pf.stop()
        self._under.reset()
        self._start()

    def batch(self):
        return self._under.batch()

    def total_examples(self):
        return self._under.total_examples()

    def input_columns(self):
        return self._under.input_columns()

    def total_outcomes(self):
        return self._under.total_outcomes()
