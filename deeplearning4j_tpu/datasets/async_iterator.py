"""AsyncDataSetIterator — background host prefetch.

Reference: datasets/iterator/AsyncDataSetIterator.java:30-105 (producer
thread + blocking queue; MultiLayerNetwork.fit wraps every iterator in one,
MultiLayerNetwork.java:1014). Same design here: a daemon thread fills a
bounded queue so host data prep overlaps device compute — the TPU infeed
double-buffering idiom.
"""

from __future__ import annotations

import queue
import threading

from deeplearning4j_tpu.datasets.iterators import DataSetIterator

_SENTINEL = object()


class AsyncDataSetIterator(DataSetIterator):
    def __init__(self, underlying: DataSetIterator, queue_size: int = 8):
        super().__init__()
        self._under = underlying
        self._size = queue_size
        self._queue: queue.Queue = queue.Queue(maxsize=queue_size)
        self._thread = None
        self._peek = None
        self._error = None
        self._stop = threading.Event()
        self._start()

    def _start(self):
        self._queue = queue.Queue(maxsize=self._size)
        self._error = None
        self._peek = None
        self._stop = threading.Event()
        stop = self._stop
        q = self._queue

        def worker():
            try:
                while not stop.is_set() and self._under.has_next():
                    item = self._under.next()
                    # bounded put that aborts promptly on stop
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
            except Exception as e:  # surfaced on the consumer side
                self._error = e
            finally:
                try:
                    q.put_nowait(_SENTINEL)
                except queue.Full:
                    # consumer is draining; it treats a dead thread as EOS
                    pass

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def _fill_peek(self):
        if self._peek is None:
            while True:
                try:
                    item = self._queue.get(timeout=0.5)
                    break
                except queue.Empty:
                    if not self._thread.is_alive():
                        item = _SENTINEL
                        break
            if item is _SENTINEL:
                if self._error is not None:
                    raise self._error
                self._peek = _SENTINEL
            else:
                self._peek = item

    def has_next(self):
        self._fill_peek()
        return self._peek is not _SENTINEL

    def next(self, num=None):
        self._fill_peek()
        if self._peek is _SENTINEL:
            raise StopIteration
        ds, self._peek = self._peek, None
        return self._apply_pre(ds)

    def reset(self):
        # signal the producer to stop, drain whatever is queued, restart
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            while True:
                try:
                    self._queue.get(timeout=0.2)
                except queue.Empty:
                    if not self._thread.is_alive():
                        break
            self._thread.join(timeout=5)
        self._under.reset()
        self._start()

    def batch(self):
        return self._under.batch()

    def total_examples(self):
        return self._under.total_examples()

    def input_columns(self):
        return self._under.input_columns()

    def total_outcomes(self):
        return self._under.total_outcomes()
