"""Vectorizers: raw input -> DataSet (reference
datasets/vectorizer/{Vectorizer,ImageVectorizer}.java).

ImageVectorizer turns one image file into a single-example DataSet with a
one-hot label, with the reference's builder-style binarize/normalize
switches (ImageVectorizer.java:75-99: binarize thresholds at 30 for
brightness-agnostic input, normalize divides by 255)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.util.image_loader import ImageLoader


class Vectorizer:
    """Anything that can produce a DataSet (reference Vectorizer.java)."""

    def vectorize(self) -> DataSet:
        raise NotImplementedError


class ImageVectorizer(Vectorizer):
    def __init__(self, image: str, num_labels: int, label: int,
                 size: Optional[tuple] = None):
        self.image = image
        self.num_labels = num_labels
        self.label = label
        self.size = size
        self._binarize = False
        self._threshold = 30
        self._normalize = False

    def binarize(self, threshold: int = 30) -> "ImageVectorizer":
        """Pixel > threshold -> 1 else 0 (brightness agnostic)."""
        self._binarize = True
        self._threshold = threshold
        self._normalize = False
        return self

    def normalize(self) -> "ImageVectorizer":
        """Scale pixel values to [0, 1]."""
        self._normalize = True
        self._binarize = False
        return self

    def vectorize(self) -> DataSet:
        # ImageLoader yields HWC float32 in [0, 1]
        h, w = self.size if self.size else (None, None)
        arr = ImageLoader(height=h, width=w).as_array(self.image)
        if self._binarize:
            arr = (arr * 255.0 > self._threshold).astype(np.float32)
        elif not self._normalize:
            arr = arr * 255.0  # raw pixel values, matching the reference
        x = arr[None, ...]  # single-example NHWC batch
        y = np.zeros((1, self.num_labels), np.float32)
        y[0, self.label] = 1.0
        return DataSet(x, y)
