"""Data pipeline (reference deeplearning4j-core/.../datasets)."""

from deeplearning4j_tpu.datasets.api import DataSet, MultiDataSet  # noqa: F401
from deeplearning4j_tpu.datasets.iterators import (  # noqa: F401
    ArrayDataSetIterator,
    DataSetIterator,
    ExistingDataSetIterator,
    ListDataSetIterator,
    MultipleEpochsIterator,
    SamplingDataSetIterator,
)
from deeplearning4j_tpu.datasets.async_iterator import AsyncDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.cifar import CifarDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.iris import IrisDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.lfw import LFWDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.curves import (  # noqa: F401
    CurvesDataFetcher,
    CurvesDataSetIterator,
)
from deeplearning4j_tpu.datasets.image_records import (  # noqa: F401
    ImageRecordReader,
    ImageRecordReaderDataSetIterator,
)
