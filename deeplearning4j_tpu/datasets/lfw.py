"""LFW (Labeled Faces in the Wild) dataset iterator.

Reference: `datasets/iterator/impl/LFWDataSetIterator.java` +
`fetchers/LFWDataFetcher.java` — downloads the LFW tarball, walks
`lfw/<person>/<image>.jpg`, and feeds face crops through the image
pipeline. This environment has zero egress, so when no local LFW copy
exists a deterministic synthetic face corpus is generated ONCE into the
same `<person>/<image>.png` directory layout and then read back through
the real `ImageRecordReader` file pipeline — the loader/reader path under
test is identical to the real-data path; only the pixels are synthetic.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.image_records import (
    ImageRecordReader,
    ImageRecordReaderDataSetIterator,
)
from deeplearning4j_tpu.util.image_loader import ImageLoader

_DEFAULT_DIR = os.path.expanduser("~/.deeplearning4j_tpu/lfw")


def _synthesize_person(rng: np.random.Generator, size: int) -> np.ndarray:
    """A per-person base 'face': smooth low-frequency blob structure."""
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    base = np.zeros((size, size, 3), np.float32)
    for _ in range(4):
        cx, cy = rng.random(2)
        sx, sy = 0.08 + 0.25 * rng.random(2)
        amp = rng.random(3)
        blob = np.exp(-(((xx - cx) / sx) ** 2 + ((yy - cy) / sy) ** 2))
        base += blob[..., None] * amp
    return base / max(base.max(), 1e-6)


def generate_synthetic_lfw(root: str, n_people: int = 10,
                           images_per_person: int = 8, size: int = 32,
                           seed: int = 123) -> None:
    """Write `<root>/<person>/<img>.png` once (idempotent)."""
    marker = os.path.join(root, ".synthetic_complete")
    if os.path.exists(marker):
        return
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(seed)
    for p in range(n_people):
        person = f"person_{p:03d}"
        d = os.path.join(root, person)
        os.makedirs(d, exist_ok=True)
        base = _synthesize_person(rng, size)
        for i in range(images_per_person):
            img = np.clip(base + 0.08 * rng.standard_normal(base.shape), 0, 1)
            ImageLoader.save(img, os.path.join(d, f"{person}_{i:04d}.png"))
    with open(marker, "w") as f:
        f.write("ok")


def _has_real_lfw(root: str) -> bool:
    if not os.path.isdir(root):
        return False
    if os.path.exists(os.path.join(root, ".synthetic_complete")):
        return True  # synthetic corpus already materialized
    subdirs = [d for d in os.listdir(root)
               if os.path.isdir(os.path.join(root, d))]
    return len(subdirs) > 0


class LFWDataSetIterator(ImageRecordReaderDataSetIterator):
    """Reference LFWDataSetIterator: batches of face images + one-hot
    person labels. Points `data_dir` at a real LFW extraction to use the
    actual dataset; otherwise a synthetic corpus in the same layout is
    generated and used."""

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 image_size: int = 32, channels: int = 3,
                 data_dir: Optional[str] = None, shuffle: bool = False,
                 seed: int = 123, n_people: int = 10,
                 images_per_person: int = 8):
        root = data_dir or _DEFAULT_DIR
        if not _has_real_lfw(root):
            generate_synthetic_lfw(root, n_people=n_people,
                                   images_per_person=images_per_person,
                                   size=image_size, seed=seed)
        reader = ImageRecordReader(root, image_size, image_size, channels)
        if num_examples is not None:
            reader._files = reader._files[:num_examples]
        super().__init__(reader, batch_size, shuffle=shuffle, seed=seed)
