"""CIFAR-10 pipeline (reference iterator/impl/CifarDataSetIterator.java).

Parses the standard binary batch format when present locally; zero-egress
fallback is a deterministic synthetic set with the same shapes ([N,32,32,3]
NHWC float32), keeping VGG/ResNet benchmarks runnable offline.
"""

from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator

_DEFAULT_DIR = os.path.expanduser("~/.deeplearning4j_tpu/cifar10")


def _load_local(data_dir: str, train: bool):
    """cifar-10-batches-py pickle format (or the tar.gz containing it)."""
    batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
    tar = os.path.join(data_dir, "cifar-10-python.tar.gz")
    if not os.path.isdir(batch_dir) and os.path.exists(tar):
        with tarfile.open(tar) as tf:
            tf.extractall(data_dir)  # noqa: S202
    if not os.path.isdir(batch_dir):
        return None
    names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
    xs, ys = [], []
    for n in names:
        with open(os.path.join(batch_dir, n), "rb") as f:
            d = pickle.load(f, encoding="bytes")  # noqa: S301
        xs.append(d[b"data"])
        ys.extend(d[b"labels"])
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return x.astype(np.float32) / 255.0, np.asarray(ys, np.int64)


def _synthetic_cifar(n: int, seed: int, train: bool):
    rng = np.random.default_rng(seed + (0 if train else 1))
    yy, xx = np.mgrid[0:32, 0:32] / 31.0
    templates = np.stack([
        np.stack([
            np.sin((c + 1) * np.pi * xx + ch),
            np.cos((c % 5 + 1) * np.pi * yy + ch),
            np.sin((c % 3 + 1) * 2 * np.pi * (xx * yy) + ch),
        ], axis=-1)
        for c in range(10) for ch in [0.0]
    ])
    templates = (templates - templates.min()) / (np.ptp(templates) + 1e-9)
    labels = rng.integers(0, 10, size=n)
    imgs = templates[labels] + rng.normal(0, 0.2, size=(n, 32, 32, 3))
    return np.clip(imgs, 0, 1).astype(np.float32), labels


class CifarDataSetIterator(ArrayDataSetIterator):
    def __init__(self, batch_size: int, num_examples: int | None = None,
                 train: bool = True, data_dir: str | None = None, seed: int = 123,
                 shuffle: bool = False):
        loaded = _load_local(data_dir or _DEFAULT_DIR, train)
        if loaded is not None:
            x, y = loaded
            self.synthetic = False
        else:
            n = num_examples or (50000 if train else 10000)
            x, y = _synthetic_cifar(n, seed, train)
            self.synthetic = True
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        if shuffle:
            rng = np.random.default_rng(seed)
            p = rng.permutation(len(x))
            x, y = x[p], y[p]
        super().__init__(x, np.eye(10, dtype=np.float32)[y], batch_size,
                         n_outcomes=10)
