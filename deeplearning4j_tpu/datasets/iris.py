"""Iris pipeline (reference fetchers/IrisDataFetcher.java + base/IrisUtils.java
+ iterator/impl/IrisDataSetIterator.java). Loads the classic 150x4 set from
scikit-learn's bundled copy (no network); normalization matches the
reference's fetcher (feature-wise standardization optional)."""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator


def load_iris_dataset(normalize: bool = True, shuffle: bool = True, seed: int = 123):
    from sklearn.datasets import load_iris  # bundled data, no download

    d = load_iris()
    x = d.data.astype(np.float32)
    y = np.eye(3, dtype=np.float32)[d.target]
    if normalize:
        x = (x - x.mean(axis=0)) / (x.std(axis=0) + 1e-8)
    if shuffle:
        rng = np.random.default_rng(seed)
        p = rng.permutation(len(x))
        x, y = x[p], y[p]
    return DataSet(x, y)


class IrisDataSetIterator(ArrayDataSetIterator):
    def __init__(self, batch_size: int = 150, num_examples: int = 150,
                 normalize: bool = True, seed: int = 123):
        ds = load_iris_dataset(normalize=normalize, seed=seed)
        super().__init__(ds.features[:num_examples], ds.labels[:num_examples],
                         batch_size, n_outcomes=3)
