"""MNIST pipeline (reference fetchers/MnistDataFetcher.java:43-125,
datasets/mnist/{MnistManager,MnistDbFile,MnistImageFile,MnistLabelFile},
base/MnistFetcher.java download, iterator/impl/MnistDataSetIterator.java:30).

Parses the standard idx file format when files are present locally (or a
download succeeds); in the zero-egress build environment it falls back to a
deterministic synthetic digit set with the same shapes/dtypes so every
downstream consumer (tests, bench) runs unchanged.

Images are [N, 784] float32 in [0,1] (reference binarize option supported),
or [N, 28, 28, 1] NHWC via `reshape_images=True` for CNN input.
"""

from __future__ import annotations

import gzip
import os
import struct
import urllib.request

import numpy as np

from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator

_BASE_URL = "https://storage.googleapis.com/cvdf-datasets/mnist/"
_FILES = {
    "train_images": "train-images-idx3-ubyte.gz",
    "train_labels": "train-labels-idx1-ubyte.gz",
    "test_images": "t10k-images-idx3-ubyte.gz",
    "test_labels": "t10k-labels-idx1-ubyte.gz",
}
_DEFAULT_DIR = os.path.expanduser("~/.deeplearning4j_tpu/mnist")


def _parse_idx(data: bytes) -> np.ndarray:
    """Parse the idx format (reference MnistDbFile reads the same headers)."""
    magic = struct.unpack(">I", data[:4])[0]
    ndim = magic & 0xFF
    dims = struct.unpack(">" + "I" * ndim, data[4:4 + 4 * ndim])
    arr = np.frombuffer(data, dtype=np.uint8, offset=4 + 4 * ndim)
    return arr.reshape(dims)


def _load_file(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        raw = f.read()
    if path.endswith(".gz") or raw[:2] == b"\x1f\x8b":
        raw = gzip.decompress(raw)
    return _parse_idx(raw)


def _try_download(data_dir: str) -> bool:
    os.makedirs(data_dir, exist_ok=True)
    try:
        for fname in _FILES.values():
            dest = os.path.join(data_dir, fname)
            if not os.path.exists(dest):
                urllib.request.urlretrieve(_BASE_URL + fname, dest)  # noqa: S310
        return True
    except Exception:
        return False


def _synthetic_mnist(n: int, seed: int, train: bool) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic digit-like images: each class is a fixed low-frequency
    template plus noise. Linearly separable enough that LeNet reaches high
    accuracy — preserves the convergence-smoke-test role of the real set."""
    rng = np.random.default_rng(seed + (0 if train else 1))
    yy, xx = np.mgrid[0:28, 0:28] / 27.0
    templates = np.stack([
        np.sin((c + 1) * np.pi * xx) * np.cos((c % 3 + 1) * np.pi * yy)
        + 0.5 * np.sin((c % 4 + 1) * 2 * np.pi * (xx + yy))
        for c in range(10)
    ])  # [10, 28, 28]
    templates = (templates - templates.min()) / (np.ptp(templates) + 1e-9)
    labels = rng.integers(0, 10, size=n)
    imgs = templates[labels] + rng.normal(0, 0.25, size=(n, 28, 28))
    imgs = np.clip(imgs, 0, 1).astype(np.float32)
    return imgs.reshape(n, 784), labels.astype(np.int64)


class MnistDataFetcher:
    """Loads (or synthesizes) the full split into memory once."""

    NUM_EXAMPLES = 60000
    NUM_EXAMPLES_TEST = 10000

    def __init__(self, train: bool = True, binarize: bool = False,
                 data_dir: str | None = None, allow_synthetic: bool = True,
                 num_examples: int | None = None, seed: int = 123):
        self.train = train
        data_dir = data_dir or _DEFAULT_DIR
        img_key = "train_images" if train else "test_images"
        lbl_key = "train_labels" if train else "test_labels"
        img_path = os.path.join(data_dir, _FILES[img_key])
        lbl_path = os.path.join(data_dir, _FILES[lbl_key])
        have = os.path.exists(img_path) and os.path.exists(lbl_path)
        if not have:
            have = _try_download(data_dir)
        if have:
            images = _load_file(img_path).astype(np.float32) / 255.0
            self.images = images.reshape(images.shape[0], -1)
            self.labels = _load_file(lbl_path).astype(np.int64)
            self.synthetic = False
        elif allow_synthetic:
            n = num_examples or (self.NUM_EXAMPLES if train else self.NUM_EXAMPLES_TEST)
            self.images, self.labels = _synthetic_mnist(n, seed, train)
            self.synthetic = True
        else:
            raise IOError(
                f"MNIST files not found in {data_dir} and download failed; "
                f"pass allow_synthetic=True or provide the idx files")
        if binarize:
            self.images = (self.images > 0.5).astype(np.float32)
        if num_examples is not None:
            self.images = self.images[:num_examples]
            self.labels = self.labels[:num_examples]


class MnistDataSetIterator(ArrayDataSetIterator):
    """Reference iterator/impl/MnistDataSetIterator.java:30."""

    def __init__(self, batch_size: int, num_examples: int | None = None,
                 train: bool = True, binarize: bool = False, shuffle: bool = False,
                 seed: int = 123, reshape_images: bool = False,
                 data_dir: str | None = None):
        f = MnistDataFetcher(train=train, binarize=binarize, data_dir=data_dir,
                             num_examples=num_examples, seed=seed)
        images, labels_idx = f.images, f.labels
        self.synthetic = f.synthetic
        if shuffle:
            rng = np.random.default_rng(seed)
            p = rng.permutation(len(images))
            images, labels_idx = images[p], labels_idx[p]
        labels = np.eye(10, dtype=np.float32)[labels_idx]
        if reshape_images:
            images = images.reshape(-1, 28, 28, 1)
        super().__init__(images, labels, batch_size, n_outcomes=10)
