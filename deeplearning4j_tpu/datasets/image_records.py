"""Image record reading — the Canova image bridge equivalent.

Reference: canova's ImageRecordReader walked a directory tree whose
subdirectory names are labels and emitted (flattened image, label index)
records consumed by RecordReaderDataSetIterator
(`deeplearning4j-core/.../datasets/canova/RecordReaderDataSetIterator.java`).
Here ImageRecordReader yields `(features [H,W,C] float32, label_index)`
tuples and ImageRecordReaderDataSetIterator batches them into NHWC
DataSets — the TPU conv layout, no flattening round-trip.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.datasets.records import RecordReader
from deeplearning4j_tpu.util.image_loader import ImageLoader

_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".ppm", ".pgm")


class ImageRecordReader(RecordReader):
    """Walks `root/<label>/<image>` and yields (array, label_idx) records.

    labels: optional explicit label order; otherwise sorted subdirectory
    names (reference parentPathLabelGenerator semantics).
    """

    def __init__(self, root: str, height: int, width: int, channels: int = 3,
                 labels: Optional[Sequence[str]] = None):
        self.root = root
        self.loader = ImageLoader(height, width, channels)
        if labels is None:
            labels = sorted(
                d for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d)))
        self.labels: List[str] = list(labels)
        self._index = {l: i for i, l in enumerate(self.labels)}
        self._files: List[tuple] = []
        for label in self.labels:
            d = os.path.join(root, label)
            for fn in sorted(os.listdir(d)):
                if fn.lower().endswith(_IMAGE_EXTS):
                    self._files.append((os.path.join(d, fn),
                                       self._index[label]))
        if not self._files:
            raise IOError(f"no image files under {root}")

    def num_examples(self) -> int:
        return len(self._files)

    def __iter__(self):
        for path, label in self._files:
            yield self.loader.as_array(path), label


class ImageRecordReaderDataSetIterator(DataSetIterator):
    """Batches an ImageRecordReader into NHWC DataSets with one-hot labels
    (the RecordReaderDataSetIterator image specialization)."""

    def __init__(self, reader: ImageRecordReader, batch_size: int,
                 shuffle: bool = False, seed: int = 123):
        super().__init__()
        self.reader = reader
        self.batch_size = batch_size
        self.shuffle = shuffle
        self._rng = np.random.default_rng(seed)
        self._records = None
        self._order = None
        self._pos = 0

    def _materialize(self):
        if self._records is None:
            feats, labels = [], []
            for arr, label in self.reader:
                feats.append(arr)
                labels.append(label)
            self._records = (np.stack(feats),
                             np.asarray(labels, np.int64))
        self._order = np.arange(len(self._records[1]))
        if self.shuffle:
            self._rng.shuffle(self._order)
        self._pos = 0

    def reset(self):
        self._materialize()

    def has_next(self) -> bool:
        if self._records is None:
            self._materialize()
        return self._pos < len(self._order)

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        sel = self._order[self._pos:self._pos + self.batch_size]
        self._pos += len(sel)
        x, y = self._records
        n_classes = len(self.reader.labels)
        onehot = np.eye(n_classes, dtype=np.float32)[y[sel]]
        return self._apply_pre(DataSet(x[sel], onehot))

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return self.reader.num_examples()

    def total_outcomes(self) -> int:
        return len(self.reader.labels)

    def get_labels(self) -> List[str]:
        return list(self.reader.labels)

    def async_supported(self) -> bool:
        return True
