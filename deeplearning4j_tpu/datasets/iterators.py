"""DataSetIterator protocol + generic iterators.

Reference: datasets/iterator/DataSetIterator.java (next(n)/batch/
totalExamples/inputColumns/reset/setPreProcessor), ListDataSetIterator,
MultipleEpochsIterator, SamplingDataSetIterator.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet


class DataSetIterator:
    """Iterator over minibatch DataSets. Python-iterable; also supports the
    reference's explicit hasNext/next protocol."""

    def __init__(self):
        self._preprocessor = None

    # -- reference protocol --
    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self, num: int | None = None) -> DataSet:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def batch(self) -> int:
        raise NotImplementedError

    def total_examples(self) -> int:
        return -1

    def input_columns(self) -> int:
        return -1

    def total_outcomes(self) -> int:
        return -1

    def async_supported(self) -> bool:
        return True

    def set_pre_processor(self, fn) -> None:
        """fn(DataSet) -> None, applied in-place to each batch (reference
        DataSetPreProcessor)."""
        self._preprocessor = fn

    def _apply_pre(self, ds: DataSet) -> DataSet:
        if self._preprocessor is not None:
            self._preprocessor(ds)
        return ds

    # -- pythonic protocol --
    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.next()


class ArrayDataSetIterator(DataSetIterator):
    """In-memory array slicing iterator — the shared engine behind the
    MNIST/CIFAR/Iris iterators (one copy of the batching contract)."""

    def __init__(self, features, labels, batch_size: int, n_outcomes: int = -1):
        super().__init__()
        self._x = features
        self._y = labels
        self._batch = batch_size
        self._outcomes = n_outcomes
        self._i = 0

    def has_next(self):
        return self._i < len(self._x)

    def next(self, num=None):
        n = num or self._batch
        sl = slice(self._i, self._i + n)
        self._i += n
        return self._apply_pre(DataSet(self._x[sl], self._y[sl]))

    def reset(self):
        self._i = 0

    def batch(self):
        return self._batch

    def total_examples(self):
        return len(self._x)

    def input_columns(self):
        return int(np.prod(self._x.shape[1:]))

    def total_outcomes(self):
        if self._outcomes > 0:
            return self._outcomes
        return int(self._y.shape[-1]) if self._y is not None else -1


class ListDataSetIterator(DataSetIterator):
    """Iterate a pre-batched or single DataSet list (reference
    ListDataSetIterator)."""

    def __init__(self, data, batch_size: int | None = None):
        super().__init__()
        if isinstance(data, DataSet):
            data = data.batch_by(batch_size) if batch_size else [data]
        elif batch_size is not None and len(data) == 1:
            data = data[0].batch_by(batch_size)
        self._data = list(data)
        self._i = 0
        self._batch = batch_size or (self._data[0].num_examples() if self._data else 0)

    def has_next(self):
        return self._i < len(self._data)

    def next(self, num=None):
        ds = self._data[self._i]
        self._i += 1
        return self._apply_pre(ds)

    def reset(self):
        self._i = 0

    def batch(self):
        return self._batch

    def total_examples(self):
        return sum(d.num_examples() for d in self._data)

    def input_columns(self):
        f = self._data[0].features
        return int(np.prod(f.shape[1:]))

    def total_outcomes(self):
        l = self._data[0].labels
        return int(l.shape[-1]) if l is not None else -1


class ExistingDataSetIterator(DataSetIterator):
    """Wrap any python iterable of DataSets."""

    def __init__(self, iterable_factory):
        super().__init__()
        if callable(iterable_factory):
            self._factory = iterable_factory
        else:
            items = list(iterable_factory)
            self._factory = lambda: iter(items)
        self._it = self._factory()
        self._peek = None

    def has_next(self):
        if self._peek is None:
            try:
                self._peek = next(self._it)
            except StopIteration:
                return False
        return True

    def next(self, num=None):
        if not self.has_next():
            raise StopIteration
        ds, self._peek = self._peek, None
        return self._apply_pre(ds)

    def reset(self):
        self._it = self._factory()
        self._peek = None

    def batch(self):
        return -1


class MultipleEpochsIterator(DataSetIterator):
    """Replays an underlying iterator N times (reference MultipleEpochsIterator)."""

    def __init__(self, epochs: int, underlying: DataSetIterator):
        super().__init__()
        self._epochs = epochs
        self._under = underlying
        self._epoch = 0

    def has_next(self):
        if self._under.has_next():
            return True
        if self._epoch + 1 < self._epochs:
            self._epoch += 1
            self._under.reset()
            return self._under.has_next()
        return False

    def next(self, num=None):
        return self._apply_pre(self._under.next(num))

    def reset(self):
        self._epoch = 0
        self._under.reset()

    def batch(self):
        return self._under.batch()


class SamplingDataSetIterator(DataSetIterator):
    """Sample `batch` examples with replacement per step (reference
    SamplingDataSetIterator)."""

    def __init__(self, dataset: DataSet, batch_size: int, total_samples: int, seed=0):
        super().__init__()
        self._ds = dataset
        self._batch = batch_size
        self._total = total_samples
        self._given = 0
        self._rng = np.random.default_rng(seed)

    def has_next(self):
        return self._given < self._total

    def next(self, num=None):
        n = num or self._batch
        idx = self._rng.integers(0, self._ds.num_examples(), size=n)
        self._given += n
        return self._apply_pre(DataSet(
            self._ds.features[idx],
            None if self._ds.labels is None else self._ds.labels[idx],
        ))

    def reset(self):
        self._given = 0

    def batch(self):
        return self._batch
