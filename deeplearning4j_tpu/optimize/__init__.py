"""Optimization: listeners + second-order solvers (reference optimize/)."""

from deeplearning4j_tpu.optimize.listeners import (  # noqa: F401
    CollectScoresIterationListener,
    IterationListener,
    ParamAndGradientIterationListener,
    PerformanceListener,
    ScoreIterationListener,
)
# the run-telemetry feed is a listener like the rest — importable from
# here alongside them. It lives in telemetry/ with the recorder it
# feeds and resolves lazily (telemetry.listener imports THIS package
# for IterationListener; an eager import here would be circular).
def __getattr__(name):
    if name == "TelemetryListener":
        from deeplearning4j_tpu.telemetry.listener import TelemetryListener
        return TelemetryListener
    raise AttributeError(name)
