"""Optimization: listeners + second-order solvers (reference optimize/)."""

from deeplearning4j_tpu.optimize.listeners import (  # noqa: F401
    CollectScoresIterationListener,
    IterationListener,
    ParamAndGradientIterationListener,
    PerformanceListener,
    ScoreIterationListener,
)
