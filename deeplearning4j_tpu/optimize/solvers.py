"""Solvers — the reference's optimize/ package, TPU-native.

Reference surface (SURVEY.md §2.2 "optimize" row):
- Solver.java:48,55 — builder + dispatch on OptimizationAlgorithm
- solvers/BaseOptimizer.java — gradientAndScore:150, optimize loop:191,
  termination checks
- solvers/StochasticGradientDescent.java:53-75
- solvers/BackTrackLineSearch.java — Armijo backtracking
- solvers/ConjugateGradient.java, solvers/LBFGS.java,
  solvers/LineGradientDescent.java
- stepfunctions/*, terminations/* (Eps, Norm2, ZeroDirection)

TPU-native redesign: the reference hand-threads INDArray views through a
mutable optimizer object. Here each solver is a pure function over a FLAT
parameter vector (ravel_pytree of the param pytree): one jitted
value-and-grad closure + jitted line-search (lax.while_loop — no
data-dependent python control flow inside jit). Curvature history (L-BFGS)
and conjugate directions live in fixed-shape device buffers so the whole
multi-iteration solve stays on-device. The updater (Adam/momentum — applied
in BaseOptimizer.updateGradientAccordingToParams:276 in the reference) is
intentionally NOT applied inside second-order solvers; like the reference,
SGD is the path that composes with updaters (nn/training.py), while
CG/L-BFGS/line-GD use raw gradients + line search.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.nn.conf.enums import OptimizationAlgorithm


# --------------------------------------------------------------------------
# Step functions (reference optimize/stepfunctions/*)
# --------------------------------------------------------------------------
class StepFunction:
    """step(params, direction, step_size) -> new params (pure)."""

    sign = 1.0

    def step(self, params, direction, step):
        return params + self.sign * step * direction


class DefaultStepFunction(StepFunction):
    sign = 1.0


class NegativeDefaultStepFunction(StepFunction):
    """The SGD default (reference NegativeDefaultStepFunction): params -= update."""

    sign = -1.0


class GradientStepFunction(StepFunction):
    sign = 1.0


class NegativeGradientStepFunction(StepFunction):
    sign = -1.0


STEP_FUNCTIONS = {
    "default": DefaultStepFunction,
    "negative_default": NegativeDefaultStepFunction,
    "gradient": GradientStepFunction,
    "negative_gradient": NegativeGradientStepFunction,
}


# --------------------------------------------------------------------------
# Termination conditions (reference optimize/terminations/*)
# --------------------------------------------------------------------------
class TerminationCondition:
    def terminate(self, new_score, old_score, direction) -> bool:
        raise NotImplementedError


class EpsTermination(TerminationCondition):
    """|new - old| < eps*|old| + tol (reference EpsTermination)."""

    def __init__(self, eps: float = 1e-4, tol: float = 1e-8):
        self.eps, self.tol = eps, tol

    def terminate(self, new_score, old_score, direction):
        return abs(new_score - old_score) < self.eps * abs(old_score) + self.tol


class Norm2Termination(TerminationCondition):
    """||direction||_2 < tolerance (reference Norm2Termination)."""

    def __init__(self, gradient_tolerance: float = 1e-6):
        self.tol = gradient_tolerance

    def terminate(self, new_score, old_score, direction):
        return float(jnp.linalg.norm(direction)) < self.tol


class ZeroDirection(TerminationCondition):
    def terminate(self, new_score, old_score, direction):
        return float(jnp.max(jnp.abs(direction))) == 0.0


DEFAULT_TERMINATIONS = (ZeroDirection(), EpsTermination())


# --------------------------------------------------------------------------
# Backtracking line search (reference solvers/BackTrackLineSearch.java)
# --------------------------------------------------------------------------
def backtrack_line_search(loss_f, x, f0, g, direction, *, initial_step=1.0,
                          rho=0.5, c1=1e-4, max_iters=16, min_step=1e-10):
    """Armijo backtracking, fully on-device via lax.while_loop.

    loss_f: flat-vector scalar loss. Finds t such that
    f(x + t*d) <= f0 + c1*t*<g,d>; halves t (rho) up to max_iters times.
    Returns (t, f(x + t*d)) — t == 0.0 if no decrease found.
    """
    slope = jnp.vdot(g, direction)

    def cond(carry):
        t, ft, it = carry
        return jnp.logical_and(
            it < max_iters,
            jnp.logical_and(t > min_step, ft > f0 + c1 * t * slope),
        )

    def body(carry):
        t, _, it = carry
        t = t * rho
        return t, loss_f(x + t * direction), it + 1

    t0 = jnp.asarray(initial_step, x.dtype)
    t, ft, _ = jax.lax.while_loop(cond, body, (t0, loss_f(x + t0 * direction), 0))
    ok = ft <= f0 + c1 * t * slope
    return jnp.where(ok, t, 0.0), jnp.where(ok, ft, f0)


# --------------------------------------------------------------------------
# Solver results
# --------------------------------------------------------------------------
@dataclass
class SolveResult:
    x: jnp.ndarray
    score: float
    iterations: int
    converged: bool


# --------------------------------------------------------------------------
# Base optimizer: host loop over jitted (value_and_grad + line-searched step)
# --------------------------------------------------------------------------
class BaseOptimizer:
    """Shared machinery (reference solvers/BaseOptimizer.java).

    loss_f(x, *args) -> scalar, pure & jittable. `*args` (minibatch, layer
    state, rng, ...) are threaded through the jitted closures as TRACED
    arguments so one optimizer instance serves every minibatch without
    retracing. Subclasses define `direction(g, aux)` and curvature updates.
    """

    def __init__(self, loss_f: Callable, max_iterations: int = 10,
                 step_function: Optional[StepFunction] = None,
                 terminations: Sequence[TerminationCondition] = DEFAULT_TERMINATIONS,
                 listeners=(), initial_step: float = 1.0,
                 max_line_search_iterations: int = 16):
        self.loss_f = loss_f
        self.vg = jax.jit(jax.value_and_grad(loss_f))
        self.max_iterations = max_iterations
        self.step_function = step_function or NegativeDefaultStepFunction()
        self.terminations = list(terminations)
        self.listeners = list(listeners)
        self.initial_step = initial_step
        self.score_value = float("nan")

        sign = self.step_function.sign

        @jax.jit
        def _line_step(x, f0, g, direction, *args):
            # search along sign*direction (NegativeDefault steps downhill
            # along +gradient-style directions)
            d = sign * direction
            # descent guard (reference BackTrackLineSearch slope check):
            # if <g,d> >= 0 the Armijo test could accept an uphill point —
            # restart with steepest descent instead
            d = jnp.where(jnp.vdot(g, d) < 0, d, -g)
            f = lambda z: loss_f(z, *args)  # noqa: E731
            t, ft = backtrack_line_search(
                f, x, f0, g, d, initial_step=initial_step,
                max_iters=max_line_search_iterations)
            return x + t * d, ft, t

        self._line_step = _line_step

    # subclass API ---------------------------------------------------------
    def init_aux(self, x, g):
        return None

    def direction(self, x, g, aux):
        """Return (direction pointing DOWNHILL-when-negated, new aux)."""
        return g, aux

    def update_aux(self, aux, x_old, x_new, g_old, g_new, d_used):
        return aux

    # main loop (reference BaseOptimizer.optimize:191) ----------------------
    def optimize(self, x0, *args) -> SolveResult:
        x = jnp.asarray(x0)
        f, g = self.vg(x, *args)
        aux = self.init_aux(x, g)
        old_f = float("inf")
        converged = False
        i = 0
        for i in range(1, self.max_iterations + 1):
            d, aux = self.direction(x, g, aux)
            x_new, f_new, t = self._line_step(x, f, g, d, *args)
            if float(t) == 0.0:  # no decrease along d — give up (ref: step==0)
                converged = True
                break
            f_new_f = float(f_new)
            _, g_new = self.vg(x_new, *args)
            # scores at x_old/x_new for subclasses (e.g. HF reduction ratio)
            self._f_pair = (float(f), f_new_f)
            aux = self.update_aux(aux, x, x_new, g, g_new, d)
            x, old_f, f, g = x_new, float(f), f_new, g_new
            self.score_value = f_new_f
            for lst in self.listeners:
                lst.iteration_done(self, i)
            if any(tc.terminate(f_new_f, old_f, d) for tc in self.terminations):
                converged = True
                break
        return SolveResult(x, float(f), i, converged)


class LineGradientDescent(BaseOptimizer):
    """Steepest descent + line search (reference LineGradientDescent.java)."""


class ConjugateGradient(BaseOptimizer):
    """Polak-Ribiere nonlinear CG with automatic restarts
    (reference solvers/ConjugateGradient.java)."""

    def init_aux(self, x, g):
        return {"d_prev": jnp.zeros_like(g), "g_prev": jnp.zeros_like(g),
                "first": True}

    def direction(self, x, g, aux):
        if aux["first"]:
            return g, dict(aux, first=False)
        g_prev, d_prev = aux["g_prev"], aux["d_prev"]
        beta = jnp.maximum(
            jnp.vdot(g, g - g_prev) / jnp.maximum(jnp.vdot(g_prev, g_prev), 1e-30),
            0.0,  # PR+ restart
        )
        return g + beta * d_prev, aux

    def update_aux(self, aux, x_old, x_new, g_old, g_new, d_used):
        return {"d_prev": d_used, "g_prev": g_old, "first": False}


class LBFGS(BaseOptimizer):
    """L-BFGS two-loop recursion with an m-deep history (reference
    solvers/LBFGS.java). History buffers are fixed-shape device arrays so the
    two-loop recursion jits cleanly (lax.fori_loop over the ring buffer)."""

    def __init__(self, loss_f, max_iterations: int = 10, m: int = 10, **kw):
        super().__init__(loss_f, max_iterations, **kw)
        self.m = m

        @partial(jax.jit, static_argnames=())
        def two_loop(g, S, Y, rho, count, head):
            """Standard two-loop recursion over ring buffers S (m,n), Y (m,n).
            Returns H*g (an ASCENT direction scaled by curvature)."""
            m = S.shape[0]
            q = g
            alphas = jnp.zeros((m,), g.dtype)

            def bwd(j, carry):
                q, alphas = carry
                idx = (head - 1 - j) % m
                valid = j < count
                a = rho[idx] * jnp.vdot(S[idx], q)
                a = jnp.where(valid, a, 0.0)
                q = q - a * Y[idx]
                return q, alphas.at[idx].set(a)

            q, alphas = jax.lax.fori_loop(0, m, bwd, (q, alphas))
            # initial Hessian scaling gamma = s'y / y'y of the newest pair
            newest = (head - 1) % m
            gamma = jnp.where(
                count > 0,
                jnp.vdot(S[newest], Y[newest])
                / jnp.maximum(jnp.vdot(Y[newest], Y[newest]), 1e-30),
                1.0,
            )
            r = gamma * q

            def fwd(j, r):
                idx = (head - count + j) % m
                valid = j < count
                b = rho[idx] * jnp.vdot(Y[idx], r)
                upd = (alphas[idx] - b) * S[idx]
                return r + jnp.where(valid, 1.0, 0.0) * upd

            return jax.lax.fori_loop(0, m, fwd, r)

        self._two_loop = two_loop

    def init_aux(self, x, g):
        n = g.shape[0]
        return {
            "S": jnp.zeros((self.m, n), g.dtype),
            "Y": jnp.zeros((self.m, n), g.dtype),
            "rho": jnp.zeros((self.m,), g.dtype),
            "count": 0,
            "head": 0,
        }

    def direction(self, x, g, aux):
        d = self._two_loop(g, aux["S"], aux["Y"], aux["rho"], aux["count"],
                           aux["head"])
        return d, aux

    def update_aux(self, aux, x_old, x_new, g_old, g_new, d_used):
        s = x_new - x_old
        y = g_new - g_old
        sy = float(jnp.vdot(s, y))
        if sy <= 1e-10:  # curvature condition failed — skip the pair
            return aux
        h = aux["head"]
        return {
            "S": aux["S"].at[h].set(s),
            "Y": aux["Y"].at[h].set(y),
            "rho": aux["rho"].at[h].set(1.0 / sy),
            "count": min(aux["count"] + 1, self.m),
            "head": (h + 1) % self.m,
        }


class StochasticGradientDescent(BaseOptimizer):
    """Plain SGD steps (reference StochasticGradientDescent.java:53-75).
    Networks normally use the fused jitted train step (nn/training.py); this
    exists for Solver-API parity and uses a fixed learning-rate step."""

    def __init__(self, loss_f, max_iterations=10, lr=0.1, **kw):
        super().__init__(loss_f, max_iterations, **kw)
        self.lr = lr

        @jax.jit
        def sgd_step(x, *args):
            f, g = jax.value_and_grad(loss_f)(x, *args)
            return x - lr * g, f

        self._sgd_step = sgd_step

    def optimize(self, x0, *args):
        x = jnp.asarray(x0)
        f = float("nan")
        for i in range(1, self.max_iterations + 1):
            x, fv = self._sgd_step(x, *args)
            f = float(fv)
            self.score_value = f
            for lst in self.listeners:
                lst.iteration_done(self, i)
        return SolveResult(x, f, self.max_iterations, True)


class HessianFree(BaseOptimizer):
    """Hessian-free (truncated-Newton) optimization — reference
    OptimizationAlgorithm.HESSIAN_FREE / StochasticHessianFree.java.

    The reference builds Gauss-Newton products by hand through its layer
    stack; here the curvature-vector product is one `jax.jvp` through the
    gradient (the R-operator), so ANY loss works unchanged. Each outer
    iteration runs damped conjugate gradient on
        (H + lam*I) d = g
    and line-searches along -d; lam adapts Levenberg-Marquardt style from
    the reduction ratio (Martens 2010, the algorithm the reference's
    StochasticHessianFree implements).
    """

    def __init__(self, loss_f, max_iterations=10, cg_iterations=32,
                 initial_lambda=1.0, **kw):
        super().__init__(loss_f, max_iterations, **kw)
        self.cg_iterations = cg_iterations
        self.lam = float(initial_lambda)

        def hvp(x, v, *args):
            return jax.jvp(lambda z: jax.grad(loss_f)(z, *args), (x,), (v,))[1]

        @partial(jax.jit, static_argnames=("iters",))
        def cg_solve(x, g, lam, *args, iters):
            def A(v):
                return hvp(x, v, *args) + lam * v

            d0 = jnp.zeros_like(g)
            r0 = g  # residual of A d = g at d = 0
            p0 = r0

            def body(carry, _):
                d, r, p, rs = carry
                Ap = A(p)
                denom = jnp.vdot(p, Ap)
                alpha = jnp.where(denom > 1e-20, rs / denom, 0.0)
                d = d + alpha * p
                r = r - alpha * Ap
                rs_new = jnp.vdot(r, r)
                beta = jnp.where(rs > 1e-20, rs_new / rs, 0.0)
                p = r + beta * p
                return (d, r, p, rs_new), None

            (d, _, _, _), _ = jax.lax.scan(
                body, (d0, r0, p0, jnp.vdot(r0, r0)), None, length=iters)
            return d

        self._cg_solve = cg_solve
        self._hvp = jax.jit(hvp)

    def direction(self, x, g, aux):
        self._last_args = getattr(self, "_opt_args", ())
        d = self._cg_solve(x, g, self.lam, *self._last_args,
                           iters=self.cg_iterations)
        # fall back to the gradient when CG fails to produce a descent dir
        ok = jnp.isfinite(d).all() & (jnp.vdot(g, d) > 0)
        d = jnp.where(ok, d, g)
        return d, aux

    def update_aux(self, aux, x_old, x_new, g_old, g_new, d_used):
        # Levenberg-Marquardt lambda adaptation from the reduction ratio
        args = self._last_args
        delta = x_new - x_old
        Hd = self._hvp(x_old, delta, *args)
        model_change = float(jnp.vdot(g_old, delta)
                             + 0.5 * jnp.vdot(delta, Hd))
        f_old, f_new = self._f_pair  # scores the optimize loop already has
        actual = f_new - f_old
        if model_change < 0:
            rho = actual / model_change
            if rho > 0.75:
                self.lam *= 2.0 / 3.0
            elif rho < 0.25:
                self.lam *= 1.5
        return aux

    def optimize(self, x0, *args):
        self._opt_args = args
        return super().optimize(x0, *args)


_OPTIMIZERS = {
    OptimizationAlgorithm.HESSIAN_FREE: HessianFree,
    OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT: StochasticGradientDescent,
    OptimizationAlgorithm.LINE_GRADIENT_DESCENT: LineGradientDescent,
    OptimizationAlgorithm.CONJUGATE_GRADIENT: ConjugateGradient,
    OptimizationAlgorithm.LBFGS: LBFGS,
}


# --------------------------------------------------------------------------
# Solver — dispatch + network integration (reference Solver.java:48,55)
# --------------------------------------------------------------------------
class Solver:
    """Optimizes a network's parameters on one batch with the configured
    algorithm. Usage (mirrors reference Solver.Builder().model(m).build()):

        Solver(model).optimize(batch_dict, rng)   # mutates model.params
    """

    def __init__(self, model, algorithm: Optional[str] = None,
                 max_iterations: Optional[int] = None, listeners=()):
        self.model = model
        g = model.conf.conf
        self.algorithm = str(algorithm or g.optimization_algo)
        self.max_iterations = max_iterations or max(1, g.iterations)
        # listeners here receive the OPTIMIZER (per inner line-search
        # iteration, score_value only) — network listeners are fired by the
        # container once per minibatch, with the network as model
        self.listeners = list(listeners)

    def get_optimizer(self, loss_f) -> BaseOptimizer:
        g = self.model.conf.conf
        cls = _OPTIMIZERS[OptimizationAlgorithm(self.algorithm)]
        kw = {}
        if cls is StochasticGradientDescent:
            kw["lr"] = g.learning_rate
        else:
            kw["max_line_search_iterations"] = max(
                1, g.max_num_line_search_iterations)
        return cls(loss_f, max_iterations=self.max_iterations,
                   listeners=self.listeners, **kw)

    def _get_cached(self, params):
        """One optimizer + one unravel for the whole fit: state/rng/batch are
        traced arguments of the jitted closures, so successive minibatches
        reuse the compiled computation (no per-batch retrace)."""
        if getattr(self, "_opt", None) is None:
            _, self._unravel = ravel_pytree(params)
            m = self.model
            unravel = self._unravel

            def loss_f(x, state, rng, batch):
                loss, _ = m._loss(unravel(x), state, rng, batch, train=True)
                return loss

            self._opt = self.get_optimizer(loss_f)
        return self._opt, self._unravel

    def optimize(self, batch, rng=None):
        m = self.model
        opt, unravel = self._get_cached(m.params)
        flat, _ = ravel_pytree(m.params)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        res = opt.optimize(flat, m.state, rng, batch)
        m.params = unravel(res.x)
        # one forward at the solution to refresh layer state (BatchNorm
        # running stats etc.) — the flat loss closure discards it
        _, (new_state, _) = m._loss(m.params, m.state, rng, batch, train=True)
        m.state = new_state
        m.score_value = res.score
        if hasattr(m, "iteration_count"):
            m.iteration_count += res.iterations
        return res
