"""Iteration listeners — the observability extension point.

Reference: optimize/api/IterationListener.java (`iterationDone(Model, int)`),
listeners/ScoreIterationListener.java, ParamAndGradientIterationListener.java;
fired per iteration from StochasticGradientDescent.java:67. UI listeners
(histogram/flow) build on the same hook (deeplearning4j_tpu/ui/).
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger("deeplearning4j_tpu")


class IterationListener:
    def iteration_done(self, model, iteration: int) -> None:
        raise NotImplementedError


class ScoreIterationListener(IterationListener):
    """Log the score every N iterations (reference ScoreIterationListener)."""

    def __init__(self, print_iterations: int = 10, printer=None):
        self.n = max(1, print_iterations)
        self.printer = printer or (lambda s: logger.info(s))

    def iteration_done(self, model, iteration):
        if iteration % self.n == 0:
            self.printer(f"Score at iteration {iteration} is {model.score_value}")


class CollectScoresIterationListener(IterationListener):
    """Collect (iteration, score) pairs in memory (reference
    CollectScoresIterationListener)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: list[tuple[int, float]] = []

    def iteration_done(self, model, iteration):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, model.score_value))


class PerformanceListener(IterationListener):
    """Per-iteration wall-clock + throughput + optional MFU (new for the
    TPU build — SURVEY.md §5 notes the reference has no profiling).

    examples_per_iteration: adds examples/sec to the report.
    flops_per_example (model fwd+bwd FLOPs, e.g. from
    models.transformer.transformer_flops_per_token x tokens/example) plus
    peak_flops (chip peak, e.g. bench.PEAK_BF16_FLOPS) adds MFU — the
    fraction of peak the fit() loop sustains. Stats are also kept on
    `.last_stats` for programmatic checks.
    """

    def __init__(self, frequency: int = 10, printer=None,
                 examples_per_iteration: int = 0,
                 flops_per_example: float = 0.0, peak_flops: float = 0.0):
        self.frequency = max(1, frequency)
        self.printer = printer or (lambda s: logger.info(s))
        self.examples_per_iteration = examples_per_iteration
        self.flops_per_example = flops_per_example
        self.peak_flops = peak_flops
        self.last_stats = {}
        self._last_time = None
        self._last_iter = 0

    def iteration_done(self, model, iteration):
        now = time.perf_counter()
        if self._last_time is not None and iteration % self.frequency == 0:
            dt = now - self._last_time
            its = iteration - self._last_iter
            if dt > 0 and its > 0:
                ips = its / dt
                msg = f"iter {iteration}: {ips:.2f} it/s"
                stats = {"iterations_per_sec": ips,
                         "score": float(model.score_value)}
                if self.examples_per_iteration:
                    eps = ips * self.examples_per_iteration
                    stats["examples_per_sec"] = eps
                    msg += f", {eps:.1f} ex/s"
                    if self.flops_per_example and self.peak_flops:
                        mfu = eps * self.flops_per_example / self.peak_flops
                        stats["mfu"] = mfu
                        msg += f", MFU {mfu:.1%}"
                self.printer(msg + f", score {model.score_value:.5f}")
                self.last_stats = stats
            self._last_time, self._last_iter = now, iteration
        elif self._last_time is None:
            self._last_time, self._last_iter = now, iteration


class ParamAndGradientIterationListener(IterationListener):
    """Parameter statistics per iteration (reference
    ParamAndGradientIterationListener; gradients are internal to the jitted
    step here, so this reports parameter norms/means)."""

    def __init__(self, frequency: int = 1, printer=None):
        import jax
        import numpy as np

        self._jax, self._np = jax, np
        self.frequency = max(1, frequency)
        self.printer = printer or (lambda s: logger.info(s))

    def iteration_done(self, model, iteration):
        if iteration % self.frequency:
            return
        np = self._np
        for name, layer in (model.params or {}).items():
            for pname, arr in layer.items():
                a = np.asarray(arr)
                self.printer(
                    f"iter {iteration} {name}/{pname}: "
                    f"mean {a.mean():.3e} absmax {np.abs(a).max():.3e} "
                    f"l2 {np.linalg.norm(a.ravel()):.3e}")


class ComposableIterationListener(IterationListener):
    def __init__(self, *listeners):
        self.listeners = listeners

    def iteration_done(self, model, iteration):
        for l in self.listeners:
            l.iteration_done(model, iteration)
