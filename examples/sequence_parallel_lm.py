"""Sequence-parallel transformer training: the TIME dimension sharded over
the mesh, attention running as the ppermute ring so no device ever holds
the full sequence — the long-context scaling path.

On CPU this creates a virtual 8-device mesh; on a TPU slice the same code
shards over the real chips.
"""
import os

import numpy as np

from deeplearning4j_tpu.util.virtual_devices import ensure_cpu_devices

# must run BEFORE any jax backend initialization
if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    ensure_cpu_devices(8)

import jax

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.models.transformer import transformer_lm
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.sequence_parallel import (
    SequenceParallelTrainer,
)
from deeplearning4j_tpu.reshard.planner import Placement

VOCAB, SEQ, BATCH = 512, 256, 4

rng = np.random.default_rng(0)
toks = np.asarray(rng.integers(0, VOCAB, (BATCH, SEQ)), np.int32)
ds = DataSet(toks, np.roll(toks, -1, axis=1))

# 2-D mesh: batch over 'data', time over 'seq' (degrade gracefully on
# hosts with few devices — e.g. one real chip). The layout is declared
# as a validated Placement (reshard/planner.py), never a raw axis dict.
n = min(8, len(jax.devices()))
data_ax = 2 if n >= 4 else 1
placement = Placement.of({"data": data_ax, "seq": n // data_ax},
                         {"data": "data", "seq": "seq"})
mesh = make_mesh(dict(placement.mesh_axes))

# the conf carries the axis name: attention becomes the K/V ring, the
# positional encodings offset by each shard's global position
net = transformer_lm(vocab_size=VOCAB, d_model=64, n_heads=4, n_layers=2,
                     d_ff=128, max_length=SEQ, seq_parallel_axis="seq")
net.init()

trainer = SequenceParallelTrainer(net, mesh, seq_axis="seq",
                                  data_axis="data")
for epoch in range(5):
    trainer.fit(ListDataSetIterator([ds]), epochs=1)
    print(f"epoch {epoch}: loss {net.score_value:.4f}")

# the SAME net serves ordinary single-host inference — outside the mesh
# the SP layers fall back to dense full-sequence attention
out = net.output(toks)
print("inference output:", out.shape)
