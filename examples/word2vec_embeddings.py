"""Word2Vec with the on-device skip-gram pipeline."""
import numpy as np

from deeplearning4j_tpu.nlp.word2vec import Word2Vec

rng = np.random.default_rng(0)
sents = []
for _ in range(800):
    i = rng.integers(0, 30)
    sents.append([f"city{i}", f"country{i}"] * 3)

w2v = (Word2Vec.builder()
       .layer_size(64)
       .window_size(2)
       .min_word_frequency(1)
       .negative_sample(5)
       .epochs(3)
       .use_device_pipeline(True)   # corpus on device, one scan per epoch
       .build())
w2v.fit(sents)

print("sim(city3, country3) =", w2v.similarity("city3", "country3"))
print("sim(city3, country17) =", w2v.similarity("city3", "country17"))
print("nearest to city5:", w2v.words_nearest("city5", 3))

from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer
WordVectorSerializer.write_word_vectors(w2v, "/tmp/vectors.txt")
print("saved /tmp/vectors.txt")
