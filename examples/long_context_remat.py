"""Long-context training with rematerialization: conf.remat wraps every
layer vertex in jax.checkpoint, so per-layer activations are recomputed
during the backward pass instead of living in HBM for the whole step —
the HBM-for-FLOPs trade that lets sequence lengths train on one chip
that would otherwise OOM (reference memory knobs: the workspace system;
here the XLA-native equivalent).

Composes with the flash-attention kernels (attention never materializes
[T, T] scores either way) and with every set_mesh axis. For sequences
too long for ONE chip even with remat, see sequence_parallel_lm.py —
the two compose: remat shrinks per-shard activation memory under the
seq axis too.
"""
import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.models.transformer import transformer_lm

VOCAB, SEQ, BATCH = 512, 1024, 2

rng = np.random.default_rng(0)
toks = np.asarray(rng.integers(0, VOCAB, (BATCH, SEQ)), np.int32)
ds = DataSet(toks, np.roll(toks, -1, axis=1))

net = transformer_lm(vocab_size=VOCAB, d_model=64, n_heads=2, n_layers=4,
                     d_ff=128, max_length=SEQ, remat=True)
net.init()

for epoch in range(5):
    net.fit(ListDataSetIterator([ds]), epochs=1)
    print(f"epoch {epoch}: loss {net.score_value:.4f}")
