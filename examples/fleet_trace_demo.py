"""Fleet trace timeline end to end: launch a 2-process fleet, merge its
per-process telemetry shards, print per-span statistics, run the
anomaly detector, and export a Perfetto-openable timeline.

Each worker writes its own `<path>.pN` shard (the launcher's process-id
env makes telemetry/recorder.py suffix the shared path), emitting the
registered span/event schema: a `compile` span around the first fit
(the real trace+compile cost), one `step` event per global step — every
process stamps step N with the SAME `step-<n>` trace id, the
cross-process correlation the straggler detector joins on — and
pipelined `input_wait` spans from the data/ prefetch channel.

    JAX_PLATFORMS=cpu python examples/fleet_trace_demo.py [telemetry_path]

Then explore the same shards by hand:

    python tools/tracetool.py stats  <telemetry_path>
    python tools/tracetool.py check  <telemetry_path>
    python tools/tracetool.py export <telemetry_path> --perfetto
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "fleet_trace_demo.jsonl")


def worker() -> None:
    """One fleet member: a tiny MLP trained with the elastic-style
    global-step loop, batches dequeued through the prefetch channel."""
    import numpy as np

    from deeplearning4j_tpu.data.pipeline import iter_prefetched
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn.conf import (DenseLayer,
                                            NeuralNetConfiguration,
                                            OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.training import fit_steps
    from deeplearning4j_tpu.telemetry.recorder import get_default

    rec = get_default()
    rec.meta(role="fleet-trace-demo-worker")
    rng = np.random.default_rng(0)

    def batch(i: int) -> DataSet:
        x = rng.normal(size=(8, 12)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        return DataSet(x, y)

    conf = (NeuralNetConfiguration.builder().seed(7).list()
            .layer(DenseLayer(n_in=12, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    # the first fit IS the compile: span-named so the merged timeline
    # shows each process's compile cost (the warmup flag is a serving
    # concept; training compiles are the expected first-dispatch price)
    with rec.span("compile", what="first_fit"):
        net.fit(batch(0))
    fit_steps(net, batch, total_steps=8)
    # a short prefetched pass puts pipelined input_wait spans on the
    # record — the starve-proof signal the spike detector watches
    data = [batch(i) for i in range(6)]
    for _ds, _row in iter_prefetched(ListDataSetIterator(data),
                                     lambda ds: ds, depth=2,
                                     recorder=rec):
        pass
    rec.close()


def main() -> int:
    tpath = sys.argv[1] if len(sys.argv) > 1 else DEFAULT_PATH
    from deeplearning4j_tpu.distributed.launcher import launch_local
    from deeplearning4j_tpu.telemetry import trace as trace_mod

    results = launch_local(
        [sys.executable, os.path.abspath(__file__), "--worker"],
        n_processes=2, local_device_count=1, timeout=300.0,
        extra_env={"DL4J_TPU_TELEMETRY": tpath})
    bad = [r for r in results if r.returncode != 0]
    if bad:
        for r in bad:
            print(f"[p{r.process_id}] rc={r.returncode}\n" + r.output[-2000:])
        return 1
    timeline = trace_mod.load_timeline(tpath)
    print(f"merged {len(timeline.events)} events from "
          f"{timeline.processes}")
    for (proc, name), row in sorted(trace_mod.span_stats(timeline).items()):
        print(f"  {proc:<6} {name:<14} n={row['count']:<3} "
              f"p50={row['p50_ms']:.3f}ms p99={row['p99_ms']:.3f}ms")
    findings = trace_mod.detect_anomalies(timeline)
    print(f"anomalies: {len(findings)}")
    for f in findings:
        print("  " + json.dumps(f))
    out = tpath + ".perfetto.json"
    with open(out, "w") as fh:
        json.dump(trace_mod.to_perfetto(timeline), fh)
    print(f"perfetto timeline -> {out} (open at https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        sys.exit(main())
