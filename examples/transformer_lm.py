"""6-layer Transformer LM through the DAG builder API with MFU reporting."""
import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.models.transformer import (
    transformer_flops_per_token,
    transformer_lm,
)
from deeplearning4j_tpu.optimize.listeners import PerformanceListener

VOCAB, SEQ, BATCH = 1000, 128, 8
net = transformer_lm(vocab_size=VOCAB, d_model=128, n_heads=2, n_layers=6,
                     d_ff=512, max_length=SEQ)
net.init()
net.set_listeners(PerformanceListener(
    frequency=4, printer=print, examples_per_iteration=BATCH * SEQ,
    flops_per_example=transformer_flops_per_token(VOCAB, 128, 6, 512, SEQ),
    peak_flops=197e12))  # v5e; informational on CPU

rng = np.random.default_rng(0)
toks = np.asarray(rng.integers(0, VOCAB, (BATCH, SEQ)), np.int32)
# sparse integer labels: next-token targets, no one-hot materialization
ds = DataSet(toks, np.roll(toks, -1, axis=1))
net.fit(ListDataSetIterator([ds] * 8), epochs=3)
print("final loss:", net.score_value)
