"""ResNet-20 under allreduce data parallelism on a device mesh.

On CPU this creates a virtual 8-device mesh; on a TPU slice the same code
shards over the real chips.
"""
import os

import numpy as np

from deeplearning4j_tpu.util.virtual_devices import ensure_cpu_devices

# must run BEFORE any jax backend initialization
if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    ensure_cpu_devices(8)

import jax

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.models.resnet import resnet20
from deeplearning4j_tpu.parallel.data_parallel import (
    DataParallelTrainer,
    ParameterAveragingTrainer,
)
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.reshard.search import FleetShape, search_placement

rng = np.random.default_rng(0)
x = rng.random((64, 32, 32, 3), dtype=np.float32)
y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)]
batches = ListDataSetIterator([DataSet(x, y)] * 4)

net = resnet20()
net.init()

# the cost model picks the mesh (automatic placement search,
# reshard/search.py): pure dp over every visible device wins this
# fleet shape, and the trainers consume the winner's axes instead of a
# hand-guessed layout
search = search_placement(net, FleetShape(1, min(8, len(jax.devices()))))
mesh = make_mesh(dict(search.winner.mesh_axes))
DataParallelTrainer(net, mesh).fit(batches)        # in-step allreduce
print("allreduce DP loss:", net.score_value)
print("sharded eval accuracy:", net.evaluate(DataSet(x, y)).accuracy())

net2 = resnet20()
net2.init()
ParameterAveragingTrainer(net2, mesh, averaging_frequency=2).fit(batches)
print("param-averaging loss:", net2.score_value)   # reference-parity mode
