"""Mixture-of-Experts LM with routed dispatch + expert parallelism.

Each transformer block's feed-forward is a bank of expert FFNs behind a
learned top-k router (nn/layers/moe.py): tokens are dispatched to their
experts through static-shaped capacity-factor einsums (GShard-style — no
gather/scatter of ragged token groups, so XLA tiles everything onto the
MXU), and a Switch-style load-balance loss keeps the router honest.

`set_mesh(axes={"expert": ...})` shards the stacked expert tensors over a
mesh axis; GSPMD inserts the combine psum — the same public entry point
as data/model/pipe/seq parallelism, and they compose (dp x ep below).

On CPU this creates a virtual 8-device mesh; on a TPU slice the same code
shards over the real chips.
"""
import os

import numpy as np

from deeplearning4j_tpu.util.virtual_devices import ensure_cpu_devices

# must run BEFORE any jax backend initialization
if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    ensure_cpu_devices(8)

import jax

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.models.transformer import transformer_moe_lm
from deeplearning4j_tpu.reshard.planner import Placement

VOCAB, SEQ, BATCH = 512, 64, 8

rng = np.random.default_rng(0)
toks = np.asarray(rng.integers(0, VOCAB, (BATCH, SEQ)), np.int32)
labels = np.eye(VOCAB, dtype=np.float32)[np.roll(toks, -1, axis=1)]
ds = DataSet(toks, labels)

net = transformer_moe_lm(
    vocab_size=VOCAB, d_model=64, n_heads=2, n_layers=2,
    n_experts=8, top_k=2, d_expert_hidden=128, max_length=SEQ,
    routing="routed",        # capacity-factor dispatch (default);
    capacity_factor=1.25,    # "dense" = compute-all-experts oracle
)
net.init()

# data x expert: batch sharded over 'data', experts over 'expert' — a
# declarative Placement (reshard/planner.py) the unified set_mesh entry
# consumes directly, instead of a hand-constructed mesh + role dict
placement = Placement.of({"data": 2, "expert": 4},
                         {"data": "data", "expert": "expert"})
net.set_mesh(placement)

print(f"devices: {len(jax.devices())}, "
      f"mesh: {dict(placement.mesh_axes)}")
print("expert tensor sharding:",
      net.params["blk0_moe"]["We1"].sharding.spec)

for epoch in range(5):
    net.fit(ds)
    print(f"epoch {epoch}: loss {float(net.score_value):.4f}")

out = net.output(toks)
print("output:", np.asarray(out[0]).shape)
