"""LeNet-5 on MNIST through the sequential builder API."""
import numpy as np

from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.models.lenet import lenet5
from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener
from deeplearning4j_tpu.util.model_serializer import ModelSerializer

train = MnistDataSetIterator(batch_size=128, num_examples=2048,
                             reshape_images=True, shuffle=True)
test = MnistDataSetIterator(batch_size=256, num_examples=512, train=False,
                            reshape_images=True)

net = lenet5(learning_rate=2e-3)
net.init()
net.set_listeners(ScoreIterationListener(print_iterations=16, printer=print))

# fused-epoch training: each epoch is one device dispatch
net.fit_scanned(train, epochs=4)
print("epoch losses:", [round(float(x), 4) for x in net._epoch_losses])

ev = net.evaluate(test)
print(ev.stats())

ModelSerializer.write_model(net, "/tmp/lenet.zip")
restored = ModelSerializer.restore_multi_layer_network("/tmp/lenet.zip")
test.reset()
print("restored accuracy:", restored.evaluate(test).accuracy())
