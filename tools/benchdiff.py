#!/usr/bin/env python
"""benchdiff — the bench-artifact regression detector.

    python tools/benchdiff.py BENCH_r04.json BENCH_r05.json
    python tools/benchdiff.py OLD NEW --threshold 0.05
    python tools/benchdiff.py OLD NEW --json

Compares two bench artifacts (driver BENCH_r*.json wrappers, raw
bench.py stdout, or telemetry JSONL logs — anything
`telemetry/artifact.py` can parse, including tail-truncated artifacts
whose rows are reconstructed from the gate-carrying summary line) and
names EVERY changed metric with old/new/delta. Exit codes: 0 no
regression, 1 regression past threshold, 2 usage error.

Kernel tuning tables (deeplearning4j_tpu/ops/tuning_table.json — both
files carrying `{"version", "entries"}`) diff entry-wise instead: every
changed entry is named with its old/new params and best-timing delta%.
Regressions there are (a) an entry's `best_us` growing past the
threshold (timings are lower-is-better) and (b) a match-or-beat
violation — `best_us` exceeding the entry's own `default_us`, which the
kerneltune harness guarantees never happens in a healthy sweep.

SERVE artifacts (tools/trafficreplay.py / bench.py serving_replay /
serving_generate, and the --fleet SERVE_r03 shape) diff through
the same path with INVERTED direction for their latency rows: a line
carrying `lower_is_better: true`, or a `*_p50_ms`/`*_p99_ms`/
`*_ttft_*_ms`/`*recompiles`/`*occupancy`/`*failed_requests`-shaped
name recovered from a summary line, regresses when its value GROWS past
the threshold (and a retrace count rising from 0 always regresses).
The fleet rows `swap_ms`/`respawn_ms` ride the `_ms` rule. QPS and
tokens/sec stay higher-is-better.

PLAN artifacts (cli `plan --artifact` / bench.py placement_search —
the automatic placement search) diff the same way: per-candidate
score rows (`plan_score::...`, `plan_predicted::...`), measured
step rows (`plan_measured_ms::...`), and the winner-score rows are
lower-is-better; a changed `winner` string field is always NAMED as a
change; and `predicted_rank_violations` regresses on ANY increase
(like retraces — the cost model ordered a confidently-separated pair
against the measurement).

TRACE artifacts (tools/tracetool.py stats --artifact — the merged
fleet timeline) diff the same way: per-(process, span) latency rows
(`trace_span_p50_ms::...`/`trace_span_p99_ms::...`) are
lower-is-better via the `_ms` rule, and the detector rows
`anomaly_count` / `straggler_skew_ms` regress on ANY increase — an
anomaly appearing, or the fleet's step skew growing at all, is never
an improvement.

MEM/COST rows (bench.py's memory headline + tracetool's
`trace_hbm_peak_bytes` + PLAN `plan_measured_bytes::...`) are
lower-is-better by name — `hbm_peak_bytes`, `mem_*_bytes`,
`peak_temp_bytes` growing past threshold is a memory regression —
and `leak_count` / `cost_drift_ratio` regress on ANY increase (the
retrace rise-from-zero rule: the first leak or first out-of-band
cost-model drift moves the value off 0, which a percentage threshold
would wave through). `mfu_live` stays higher-is-better.

EMBED artifacts (bench.py embed — the sharded embedding engine +
ANN serving, EMBED_r01.json) add four row families:
`queries_per_sec` and `recall_at_k` stay higher-is-better (serving
throughput dropping or ANN recall falling past threshold is the
regression); `scatter_add_us` rides the `_us` rule (the sparse
scatter-add step slowing down); and `ep_gather_bytes` is
lower-is-better by name — the per-device gather traffic growing
means the ep sharding stopped splitting the table (the ep=2 row
should carry ~half the ep=1 bytes).

What counts as a regression (bench metrics are higher-is-better unless
flagged lower-is-better as above):

* a metric value dropping more than `--threshold` (default 10%), with
  chip-state slack: when the new line carries `gate_scale` (the bench's
  measured probe/healthy ratio), the allowed drop grows by the measured
  throttle so a slow shared-tenancy window doesn't read as a code
  regression — the same philosophy as bench.py's own gate;
* a `regression: true` flag present in NEW but not OLD;
* a gated quality ratio (`quality_ratio_vs_host` vs
  `quality_gate_min_ratio`, `vs_dense_ratio` vs `ratio_floor`) falling
  below its floor in NEW.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import re
import sys
import types

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_THRESHOLD = 0.10

# Serving latency metrics are LOWER-is-better: their lines carry
# `lower_is_better: true` (serving/replay.py), and the name pattern
# covers rows reconstructed from a summary line (which keeps only the
# value) — p50/p99/_ms latency and retrace counts from SERVE artifacts,
# plus RESHARD artifact rows (cli reshard dry run): bytes_moved /
# bytes_lower_bound / plan-time _us growth is the regression direction,
# INPUT artifact rows (bench input_pipeline): input_wait stall
# percentiles growing past threshold is the starvation regression,
# and FLEET rows (trafficreplay --fleet, SERVE_r03): swap_ms /
# respawn_ms ride the _ms rule, autoscale occupancy the occupancy rule,
# and failed_requests growing is dropped traffic — never an improvement.
# SPECULATIVE rows (SERVE_r04) ride the _us rule (sample_us /
# draft_overhead_us) and add _mismatches: the parity gates count greedy
# token-stream divergences vs the baseline arm — while
# accepted_tokens_per_step stays higher-is-better (no pattern match).
# MEM/COST rows (bench.py _memory_rows, tracetool TRACE artifacts,
# PLAN plan_measured_bytes) are byte headlines: hbm_peak_bytes /
# mem_*_bytes / peak_temp_bytes growing is a memory regression —
# while mfu_live stays higher-is-better (no pattern match).
_LOWER_IS_BETTER_RE = re.compile(
    r"(_p\d+_ms$|_ms$|latency|recompiles|bytes_moved$|bytes_lower_bound$"
    r"|_us$|_ttft_|occupancy|input_wait|failed_requests$|_mismatches$"
    r"|plan_predicted|plan_winner|plan_score|plan_measured"
    r"|rank_violations$|anomaly_count$|trace_span_"
    r"|hbm_peak_bytes|mem_\w*_bytes|peak_temp_bytes|leak_count"
    r"|cost_drift_ratio|ep_gather_bytes)")

# leak_count and cost_drift_ratio regress on ANY increase (below): a
# run that introduces its FIRST leak or its first out-of-band
# cost-model drift moved 0 -> n, which a percentage threshold on a
# zero baseline would wave through — the retrace rise-from-zero rule.

# Metrics where ANY growth regresses regardless of threshold: a
# predicted-vs-measured rank violation (PLAN artifacts, bench.py
# placement_search) means the cost model confidently ordered a pair
# against the measurement — like a retrace count, there is no
# acceptable increase. TRACE artifacts add the detector rows: one new
# anomaly, or any growth in the fleet's step-completion skew, is a
# health regression however small the percentage. Parity mismatches
# (SERVE_r04 speculative/quantized arms) are the same class: greedy
# output is bit-identical by construction, so a single divergence is a
# correctness break, not a tolerable drift.
_ALWAYS_REGRESS_RE = re.compile(
    r"(rank_violations$|anomaly_count$|straggler_skew_ms$"
    r"|_parity_mismatches$|leak_count$|cost_drift_ratio)")


def _lower_is_better(metric: str, old: dict, new: dict) -> bool:
    if old.get("lower_is_better") or new.get("lower_is_better"):
        return True
    return bool(_LOWER_IS_BETTER_RE.search(str(metric)))

# gate fields that are themselves higher-is-better measurements worth
# diffing (context fields like gate_scale/floors are reported, not judged)
_JUDGED_GATE_FIELDS = ("quality_ratio_vs_host", "vs_dense_ratio",
                       "mfu_vs_achievable", "mfu_executed")
_GATED_PAIRS = (("quality_ratio_vs_host", "quality_gate_min_ratio"),
                ("vs_dense_ratio", "ratio_floor"))


def _artifact_mod():
    """Import telemetry.artifact without the package root (which pulls
    the full nn stack + jax) — the tools/graftlint.py stub idiom; a
    fully imported real package (the test environment) is left alone."""
    sys.path.insert(0, ROOT)
    for name in ("deeplearning4j_tpu", "deeplearning4j_tpu.telemetry"):
        if name not in sys.modules:
            mod = types.ModuleType(name)
            mod.__path__ = [os.path.join(ROOT, *name.split("."))]
            sys.modules[name] = mod
    return importlib.import_module("deeplearning4j_tpu.telemetry.artifact")


def _num(line, key):
    v = line.get(key)
    return v if isinstance(v, (int, float)) and not isinstance(v, bool) \
        else None


# ------------------------------------------------------- tuning tables

def load_tuning_table(path: str) -> dict | None:
    """The parsed table when `path` is a kerneltune artifact, else
    None (fall through to the bench-artifact parser)."""
    try:
        with open(path) as fh:
            obj = json.load(fh)
    except (OSError, ValueError):
        return None
    if isinstance(obj, dict) and "version" in obj and \
            isinstance(obj.get("entries"), dict):
        return obj
    return None


def _entry_params(entry: dict) -> dict:
    meta = ("best_us", "default_us", "candidates", "source")
    return {k: v for k, v in entry.items() if k not in meta}


def diff_tables(old: dict, new: dict,
                threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Entry-wise tuning-table diff, same result shape as diff() so
    render()/--json consumers are shared. Timings are lower-is-better:
    best_us GROWING past the threshold is the regression direction, and
    a match-or-beat violation (best_us > default_us in NEW) always
    regresses — kerneltune never writes one."""
    o_e, n_e = old.get("entries", {}), new.get("entries", {})
    regressions, changes = [], []
    added = sorted(k for k in n_e if k not in o_e)
    removed = sorted(k for k in o_e if k not in n_e)
    for key in sorted(set(o_e) & set(n_e)):
        oe, ne = o_e[key], n_e[key]
        op, np_ = _entry_params(oe), _entry_params(ne)
        if op != np_:
            changes.append({"metric": key, "field": "params",
                            "old": op, "new": np_, "delta_pct": None})
        o_us, n_us = _num(oe, "best_us"), _num(ne, "best_us")
        if o_us is not None and n_us is not None and o_us != n_us:
            delta_pct = round(100.0 * (n_us - o_us) / abs(o_us), 2) \
                if o_us else None
            row = {"metric": key, "field": "best_us", "old": o_us,
                   "new": n_us, "delta_pct": delta_pct}
            if o_us > 0 and (n_us - o_us) / o_us > threshold:
                row["reason"] = (f"best_us grew {delta_pct:.1f}% "
                                 f"(> {100 * threshold:.0f}% allowed — "
                                 "timings are lower-is-better)")
                regressions.append(row)
            else:
                changes.append(row)
        n_dflt = _num(ne, "default_us")
        if n_us is not None and n_dflt is not None and n_us > n_dflt:
            regressions.append({
                "metric": key, "field": "best_us", "old": n_dflt,
                "new": n_us, "delta_pct": None,
                "reason": f"match-or-beat violated: best_us {n_us} > "
                          f"default_us {n_dflt}"})
    return {"regressions": regressions, "changes": changes,
            "added": added, "removed": removed}


def diff(old_lines: dict, new_lines: dict,
         threshold: float = DEFAULT_THRESHOLD) -> dict:
    """{metric: line} x2 -> {regressions, changes, added, removed}.

    Every entry in `regressions`/`changes` names the metric and field
    with old/new/delta_pct; `regressions` alone drives the exit code."""
    regressions, changes = [], []
    added = sorted(m for m in new_lines if m not in old_lines
                   and m != "summary")
    removed = sorted(m for m in old_lines if m not in new_lines
                     and m != "summary")
    for metric in sorted(set(old_lines) & set(new_lines) - {"summary"}):
        old, new = old_lines[metric], new_lines[metric]
        gate_scale = _num(new, "gate_scale")
        slack = max(0.0, 1.0 - gate_scale) if gate_scale is not None else 0.0
        lower_better = _lower_is_better(metric, old, new)
        for field in ("value",) + _JUDGED_GATE_FIELDS:
            o, n = _num(old, field), _num(new, field)
            if o is None or n is None or o == n:
                continue
            delta_pct = round(100.0 * (n - o) / abs(o), 2) if o else None
            row = {"metric": metric, "field": field, "old": o, "new": n,
                   "delta_pct": delta_pct}
            if lower_better and field == "value":
                # lower-is-better (SERVE latency/retraces): GROWTH past
                # the threshold is the regression direction; a retrace
                # count rising from 0 always regresses (no ratio exists
                # for a zero base — any retrace means the bucket lattice
                # leaked), and rank-violation counts regress on ANY
                # increase (the placement cost model ordered a
                # confidently-separated pair against the measurement)
                grew_past = ((o > 0 and (n - o) / o > threshold + slack)
                             or (o == 0 and n > 0)
                             or (n > o
                                 and _ALWAYS_REGRESS_RE.search(str(metric))))
                if grew_past:
                    row["reason"] = (
                        f"{field} grew"
                        + (f" {delta_pct:.1f}%" if delta_pct is not None
                           else f" {o} -> {n}")
                        + f" (> {100 * (threshold + slack):.0f}% allowed "
                          "— lower is better)")
                    regressions.append(row)
                else:
                    changes.append(row)
                continue
            dropped_past = (o > 0 and (o - n) / o > threshold + slack)
            if field == "value" and slack and o > 0 and (o - n) / o > threshold:
                row["gate_scale"] = gate_scale
            if dropped_past:
                row["reason"] = (f"{field} fell {-delta_pct:.1f}% "
                                 f"(> {100 * (threshold + slack):.0f}% "
                                 "allowed)")
                regressions.append(row)
            else:
                changes.append(row)
        # PLAN artifacts carry the winning placement as a string field:
        # a changed winner is always NAMED (a change, not a regression —
        # the scores decide regressions)
        o_win, n_win = old.get("winner"), new.get("winner")
        if isinstance(o_win, str) and isinstance(n_win, str) \
                and o_win != n_win:
            changes.append({"metric": metric, "field": "winner",
                            "old": o_win, "new": n_win,
                            "delta_pct": None})
        if new.get("regression") and not old.get("regression"):
            regressions.append({"metric": metric, "field": "regression",
                                "old": False, "new": True, "delta_pct": None,
                                "reason": "regression flag newly set"})
        for ratio_field, floor_field in _GATED_PAIRS:
            r, floor = _num(new, ratio_field), _num(new, floor_field)
            if r is not None and floor is not None and r < floor:
                old_r = _num(old, ratio_field)
                if old_r is None or old_r >= floor:
                    regressions.append({
                        "metric": metric, "field": ratio_field,
                        "old": old_r, "new": r, "delta_pct": None,
                        "reason": f"{ratio_field} {r} below its "
                                  f"{floor_field} {floor}"})
    return {"regressions": regressions, "changes": changes,
            "added": added, "removed": removed}


def render(result: dict, old_name: str, new_name: str,
           threshold: float) -> str:
    out = [f"benchdiff {old_name} -> {new_name} "
           f"(threshold {threshold:.0%})"]
    for row in result["regressions"]:
        out.append(f"REGRESSED {row['metric']}.{row['field']}: "
                   f"{row['old']} -> {row['new']}"
                   + (f" ({row['delta_pct']:+.1f}%)"
                      if row["delta_pct"] is not None else "")
                   + f" — {row['reason']}")
    for row in result["changes"]:
        out.append(f"changed   {row['metric']}.{row['field']}: "
                   f"{row['old']} -> {row['new']}"
                   + (f" ({row['delta_pct']:+.1f}%)"
                      if row["delta_pct"] is not None else ""))
    for m in result["added"]:
        out.append(f"added     {m}")
    for m in result["removed"]:
        out.append(f"removed   {m}")
    n = len(result["regressions"])
    out.append(f"{n} regression(s) past threshold"
               + (" -> exit 1" if n else ""))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="benchdiff", description=__doc__)
    ap.add_argument("old", help="older artifact (BENCH_r*.json / bench "
                               "stdout / telemetry JSONL)")
    ap.add_argument("new", help="newer artifact")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative value drop that counts as a regression "
                         f"(default {DEFAULT_THRESHOLD})")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    old_table = load_tuning_table(args.old)
    new_table = load_tuning_table(args.new)
    if old_table is not None and new_table is not None:
        result = diff_tables(old_table, new_table,
                             threshold=args.threshold)
    elif (old_table is None) != (new_table is None):
        print("benchdiff: cannot diff a tuning table against a bench "
              "artifact", file=sys.stderr)
        return 2
    else:
        artifact = _artifact_mod()
        try:
            old_lines = artifact.load(args.old)
            new_lines = artifact.load(args.new)
        except OSError as exc:
            print(f"benchdiff: {exc}", file=sys.stderr)
            return 2
        result = diff(old_lines, new_lines, threshold=args.threshold)
    if args.as_json:
        print(json.dumps(result, indent=1))
    else:
        print(render(result, os.path.basename(args.old),
                     os.path.basename(args.new), args.threshold))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
