#!/usr/bin/env python
"""tracetool — the fleet-timeline CLI over telemetry JSONL shards.

    python tools/tracetool.py merge  telemetry.jsonl [-o merged.jsonl]
    python tools/tracetool.py stats  telemetry.jsonl [--json]
    python tools/tracetool.py stats  telemetry.jsonl --artifact TRACE.json
    python tools/tracetool.py check  telemetry.jsonl [--json]
                                     [--fail-on straggler,retrace]
                                     [--skew-ms 2000]
    python tools/tracetool.py export telemetry.jsonl --perfetto \
                                     [-o trace.perfetto.json]
    python tools/tracetool.py tree   telemetry.jsonl [--trace <id>]
    python tools/tracetool.py mem    telemetry.jsonl [--json]

Every subcommand takes the UNSUFFIXED telemetry path and transparently
merges the `<path>.pN` per-process shards a fleet run leaves behind
(telemetry/trace.py discover_shards) — or the single file when the run
was one process.

* `merge`  — the causally-ordered union, one process-tagged JSONL line
  per event (timestamp-major; per-process seq breaks ties so no single
  process's stream ever reorders).
* `stats`  — per-(process, span-name) count/p50/p99/max/total wall
  time: where each process's time went. `--artifact` also writes the
  benchdiff-diffable TRACE artifact (per-span latency rows are
  lower-is-better; `anomaly_count`/`straggler_skew_ms` regress on ANY
  increase).
* `check`  — the anomaly detector: stragglers (cross-process
  step-completion skew / a stalled process), post-warmup retraces (the
  zero-retrace contract's runtime witness), input_wait and queue
  spikes, memory leaks (monotonic steady-state live-bytes growth),
  headroom breaches (live/limit past the watermark), and cost-model
  drift (predicted vs measured per-device memory outside the
  documented factor). Exit 1 when a finding matches `--fail-on`
  (default: every kind); the bench sweep runs this over its own
  telemetry with `--fail-on straggler,retrace,leak`. Thresholds:
  `--skew-ms`, `--leak-min-bytes`, `--watermark`, `--drift-factor`.
* `export --perfetto` — Chrome trace-event JSON; open the output at
  https://ui.perfetto.dev (or chrome://tracing). `memory` events render
  as counter ("C") tracks: live bytes + the per-subsystem ledger.
* `tree`   — render one correlated span tree (request → queue →
  batch_assemble → forward → compile); without `--trace`, lists the
  trace ids on the record.
* `mem`    — the device-memory report: per-process live-bytes timeline
  (first/last/peak, growth, last ledger breakdown, device limits), the
  compiled-cost book (per-entry flops / bytes accessed / peak temp
  from `cost` events), and every `cost_drift` reconciliation.

Exit codes: 0 clean, 1 findings (`check`), 2 usage/IO error. Pure
stdlib — importable under the tools' no-jax package stubs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _trace_mod():
    """Import telemetry.trace without the package root (which pulls the
    full nn stack + jax) — the tools/benchdiff.py stub idiom."""
    import importlib
    import types

    for name in ("deeplearning4j_tpu", "deeplearning4j_tpu.telemetry"):
        if name not in sys.modules:
            mod = types.ModuleType(name)
            mod.__path__ = [os.path.join(ROOT, *name.split("."))]
            sys.modules[name] = mod
    return importlib.import_module("deeplearning4j_tpu.telemetry.trace")


def _config(trace, args):
    kw = {}
    if getattr(args, "skew_ms", None) is not None:
        kw["straggler_skew_ms"] = float(args.skew_ms)
    if getattr(args, "leak_min_bytes", None) is not None:
        kw["leak_min_growth_bytes"] = float(args.leak_min_bytes)
    if getattr(args, "watermark", None) is not None:
        kw["headroom_watermark"] = float(args.watermark)
    if getattr(args, "drift_factor", None) is not None:
        kw["cost_drift_factor"] = float(args.drift_factor)
    return trace.AnomalyConfig(**kw)


def cmd_merge(trace, args) -> int:
    tl = trace.load_timeline(args.path)
    out = sys.stdout if args.output is None else open(args.output, "w")
    try:
        for ev in tl.events:
            out.write(json.dumps(ev) + "\n")
    finally:
        if args.output is not None:
            out.close()
            print(f"merged {len(tl.events)} events from "
                  f"{len(tl.processes)} process(es) -> {args.output}")
    return 0


def cmd_stats(trace, args) -> int:
    tl = trace.load_timeline(args.path)
    stats = trace.span_stats(tl)
    if args.as_json:
        print(json.dumps(
            {f"{p}::{n}": row for (p, n), row in sorted(stats.items())},
            indent=1))
    else:
        print(f"{len(tl.events)} events, {len(tl.processes)} process(es): "
              + ", ".join(tl.processes))
        header = (f"{'process':<8} {'span':<22} {'count':>6} "
                  f"{'p50_ms':>10} {'p99_ms':>10} {'max_ms':>10} "
                  f"{'total_s':>10}")
        print(header)
        for (p, n), row in sorted(stats.items()):
            print(f"{p:<8} {n:<22} {row['count']:>6} "
                  f"{row['p50_ms']:>10.3f} {row['p99_ms']:>10.3f} "
                  f"{row['max_ms']:>10.3f} {row['total_s']:>10.3f}")
    if args.artifact:
        anomalies = trace.detect_anomalies(tl, _config(trace, args))
        lines = trace.metric_lines(tl, anomalies)
        _write_artifact(args.artifact, lines)
        print(f"TRACE artifact ({len(lines)} rows) -> {args.artifact}")
    return 0


def _write_artifact(path: str, lines: list) -> None:
    """The SERVE/PLAN artifact shape (metric JSONL + gate-carrying
    trailing summary) so benchdiff/requote parse TRACE artifacts with
    the same code."""
    import importlib

    artifact = importlib.import_module(
        "deeplearning4j_tpu.telemetry.artifact")
    summary = artifact.build_summary(lines)
    with open(path, "w") as fh:
        for line in lines:
            fh.write(json.dumps(line) + "\n")
        fh.write(json.dumps(summary) + "\n")


def cmd_check(trace, args) -> int:
    tl = trace.load_timeline(args.path)
    findings = trace.detect_anomalies(tl, _config(trace, args))
    fail_on = set(k for k in (args.fail_on or "").split(",") if k) or None
    gating = [f for f in findings
              if fail_on is None or f["anomaly"] in fail_on]
    if args.as_json:
        print(json.dumps({"findings": findings,
                          "gating": len(gating)}, indent=1))
    else:
        for f in findings:
            gate = "FAIL" if (fail_on is None
                              or f["anomaly"] in fail_on) else "info"
            detail = {k: v for k, v in f.items() if k != "anomaly"}
            print(f"{gate} {f['anomaly']}: {json.dumps(detail)}")
        print(f"tracetool check: {len(findings)} finding(s), "
              f"{len(gating)} gating")
    return 1 if gating else 0


def cmd_export(trace, args) -> int:
    tl = trace.load_timeline(args.path)
    doc = trace.to_perfetto(tl)
    out = args.output or (args.path + ".perfetto.json")
    with open(out, "w") as fh:
        json.dump(doc, fh)
    print(f"{len(doc['traceEvents'])} trace events -> {out} "
          "(open at https://ui.perfetto.dev)")
    return 0


def cmd_tree(trace, args) -> int:
    tl = trace.load_timeline(args.path)
    if args.trace is None:
        ids = trace.trace_ids(tl)
        print(f"{len(ids)} trace(s) on the record:")
        for tid in ids:
            print(f"  {tid}")
        return 0
    roots = trace.span_tree(tl, args.trace)
    if not roots:
        print(f"tracetool: no events carry trace_id {args.trace!r}",
              file=sys.stderr)
        return 2
    print(trace.render_tree(roots))
    return 0


def cmd_mem(trace, args) -> int:
    tl = trace.load_timeline(args.path)
    report = trace.memory_report(tl)
    if args.as_json:
        print(json.dumps(report, indent=1))
        return 0
    if not report["processes"]:
        print("tracetool mem: no memory events on the record "
              "(set DL4J_TPU_MEM_EVERY / run a serving engine with "
              "telemetry enabled)")
        return 0
    for process, row in report["processes"].items():
        limits = ", ".join(f"dev{d}={v}" for d, v
                           in row["device_limits"].items()) or "none"
        print(f"{process}: {row['samples']} sample(s)  "
              f"first={row['first_bytes']}B last={row['last_bytes']}B "
              f"peak={row['peak_bytes']}B growth={row['growth_bytes']}B  "
              f"limits: {limits}")
        for subsystem, nbytes in sorted(row["ledger"].items()):
            print(f"  ledger {subsystem:<12} {nbytes}B")
    if report["cost_book"]:
        print(f"cost book ({len(report['cost_book'])} entries):")
        for key, fields in sorted(report["cost_book"].items()):
            detail = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
            print(f"  {key}: {detail}")
    for drift in report["cost_drift"]:
        print(f"cost_drift [{drift.get('source')}]: "
              f"predicted={drift.get('predicted_bytes')}B "
              f"measured={drift.get('measured_bytes')}B "
              f"ratio={drift.get('ratio')} "
              f"(factor {drift.get('factor')})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tracetool", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("path", help="telemetry JSONL path (the .pN "
                                    "shards merge transparently)")
        p.add_argument("--json", action="store_true", dest="as_json")
        p.add_argument("--skew-ms", type=float, default=None,
                       help="straggler skew threshold (default 2000)")
        p.add_argument("--leak-min-bytes", type=float, default=None,
                       help="leak growth floor in bytes (default 1 MiB)")
        p.add_argument("--watermark", type=float, default=None,
                       help="headroom breach fraction (default 0.92)")
        p.add_argument("--drift-factor", type=float, default=None,
                       help="cost-drift ratio band (default 8.0)")

    p = sub.add_parser("merge", help="merged causal timeline as JSONL")
    common(p)
    p.add_argument("-o", "--output", default=None)
    p.set_defaults(fn=cmd_merge)

    p = sub.add_parser("stats", help="per-span p50/p99 per process")
    common(p)
    p.add_argument("--artifact", default=None,
                   help="also write the benchdiff-diffable TRACE "
                        "artifact here")
    p.set_defaults(fn=cmd_stats)

    p = sub.add_parser("check", help="run the anomaly detector")
    common(p)
    p.add_argument("--fail-on", default=None,
                   help="comma list of anomaly kinds that exit 1 "
                        "(default: every kind)")
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser("export", help="export the timeline")
    common(p)
    p.add_argument("--perfetto", action="store_true",
                   help="Chrome trace-event JSON (the only format, "
                        "flag kept explicit for the reader)")
    p.add_argument("-o", "--output", default=None)
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser("tree", help="render a correlated span tree")
    common(p)
    p.add_argument("--trace", default=None, help="trace id to render")
    p.set_defaults(fn=cmd_tree)

    p = sub.add_parser("mem", help="device-memory timeline + cost book")
    common(p)
    p.set_defaults(fn=cmd_mem)

    args = ap.parse_args(argv)
    trace = _trace_mod()
    try:
        return args.fn(trace, args)
    except FileNotFoundError as exc:
        print(f"tracetool: {exc}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
