#!/usr/bin/env python
"""Regenerate the measured-performance blocks of README.md and PARITY.md
from the NEWEST driver bench artifact (BENCH_r*.json).

VERDICT r3 #7: round after round, prose tables drifted from the driver
artifacts. This script is the only writer of the blocks between
`<!-- BENCH:BEGIN -->` / `<!-- BENCH:END -->`; run it after every round:

    python tools/requote_bench.py            # newest BENCH_r*.json
    python tools/requote_bench.py BENCH_r04.json
"""

from __future__ import annotations

import glob
import importlib
import os
import re
import sys
import types

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _artifact_mod():
    """Import telemetry.artifact (the shared artifact parser, also used
    by tools/benchdiff.py) without the package root — which would pull
    the full nn stack + jax — via the tools/graftlint.py stub idiom."""
    sys.path.insert(0, ROOT)
    for name in ("deeplearning4j_tpu", "deeplearning4j_tpu.telemetry"):
        if name not in sys.modules:
            mod = types.ModuleType(name)
            mod.__path__ = [os.path.join(ROOT, *name.split("."))]
            sys.modules[name] = mod
    return importlib.import_module("deeplearning4j_tpu.telemetry.artifact")

def _mfu_str(l):
    """MFU cell: dense-accounted value, plus the executed-FLOPs figure
    when the artifact carries it (VERDICT r5 #4 — causal kernels skip
    ~half the dense-accounted attention work; artifacts before r6 lack
    the field and are labeled with their convention)."""
    s = f"{l['value']:.3f} MFU"
    if "mfu_executed" in l:
        s += f" ({l['mfu_executed']:.3f} executed-FLOPs)"
    else:
        s += " (dense-accounted)"
    return s


ROWS = [
    ("lenet_mnist_images_per_sec", "LeNet-5 / MNIST, `fit_scanned`",
     lambda l: f"{l['value'] / 1e6:.2f}M images/sec"),
    ("vgg16_cifar_images_per_sec", "VGG-16 / CIFAR-10 (DAG API)",
     lambda l: f"{l['value'] / 1e3:.1f}k images/sec"),
    ("word2vec_sgns_words_per_sec",
     "Word2Vec skip-gram NS, 1M-word zipf corpus",
     lambda l: f"{l['value'] / 1e3:.0f}k words/sec"
               + (f" (quality {l['quality']:.2f})" if "quality" in l else "")),
    ("resnet20_dp_allreduce_vs_paramavg_speedup",
     "ResNet-20 allreduce-DP vs param-averaging (virtual 8-dev mesh)",
     lambda l: f"{l['value']:.2f}x"),
    ("transformer_lm_mfu", "6-layer Transformer-LM, seq 512",
     lambda l: (f"{l['tokens_per_sec'] / 1e6:.2f}M tokens/sec, "
                if "tokens_per_sec" in l else "")
               + f"**{l['value']:.3f} MFU**"),
    ("transformer_lm_masked_mfu", "same model, variable-length masked batch",
     lambda l: f"{l['value']:.3f} MFU"),
    ("transformer_lm_masked_dropout_mfu", "same model, masked + attention dropout",
     lambda l: f"{l['value']:.3f} MFU"),
    ("transformer_lm_seq4096_tokens_per_sec",
     "same model, seq 4096 (long-context mode)",
     lambda l: f"{l['value'] / 1e3:.0f}k tokens/sec"
               + (f", {l['mfu']:.3f} MFU" if "mfu" in l else "")
               + (f" ({l['mfu_executed']:.3f} executed)"
                  if "mfu_executed" in l else "")),
    ("transformer_lm_seq32768_mfu",
     "same model, seq 32768 (chunked flash)", _mfu_str),
    ("transformer_lm_seq32768_dropout_mfu",
     "same, + padding masks + attention dropout (r6 chunk-invariant)",
     _mfu_str),
    ("transformer_lm_d1024_mfu", "d_model-1024 LM (~90M params)", _mfu_str),
    ("transformer_moe_lm_tokens_per_sec",
     "MoE-LM (8 experts, top-2)",
     lambda l: f"{l['value'] / 1e3:.0f}k tokens/sec"),
    ("ring_hop_flash_tflops", "ring-attention hop kernel",
     lambda l: f"{l['value']:.0f} TFLOP/s"
               + (f" ({l['speedup_vs_einsum_hop']:.1f}x the einsum hop)"
                  if "speedup_vs_einsum_hop" in l else "")),
    # serving rows (SERVE artifacts / the bench serving_replay mode's
    # lines in a BENCH artifact) — latency is lower-is-better, quoted
    # with QPS so the table reads as one serving line
    ("serving_replay_qps",
     "continuous-batching serving, mixed-length bursty replay",
     lambda l: f"{l['value']:.0f} req/s sustained"),
    ("serving_replay_p99_ms", "same replay, tail latency",
     lambda l: f"p99 {l['value']:.1f} ms (lower is better)"),
]


def load(path):
    """Accepts raw JSON-lines (bench.py stdout), a telemetry JSONL log,
    or the driver's wrapper object whose `tail` field holds the captured
    stdout. The driver keeps only the TAIL of that stdout, so early
    metric lines can be truncated away (r5 lost lenet/vgg/w2v/resnet/
    flagship) — rows the tail lost are reconstructed from the
    gate-carrying summary line, including every `gates[<metric>]` field
    and the regression flags (telemetry/artifact.py, VERDICT r5 #6)."""
    return _artifact_mod().load(path)


def render(lines, artifact_name):
    out = [f"Driver-captured artifact `{artifact_name}` (the authoritative "
           "record — the driver runs `python bench.py` at the end of each "
           "round; regenerate this block with `python tools/requote_bench.py`):",
           "",
           f"| benchmark (BASELINE.md config) | {artifact_name} |",
           "|---|---|"]
    for prefix, label, fmt in ROWS:
        # exact-name match: prefix rows are `metric_<backend>` lines — a
        # bare startswith could quote a cpu smoke line or a stale
        # duplicate into the docs (ADVICE r4). Prefer the tpu backend,
        # else the exact bare name; warn when several candidates match.
        match = [l for m, l in lines.items()
                 if m == prefix or m == f"{prefix}_tpu"]
        if not match:
            match = [l for m, l in lines.items()
                     if m.startswith(prefix + "_")]
            if len(match) > 1:
                print(f"warning: {len(match)} metrics match prefix "
                      f"{prefix!r}; quoting the last", file=sys.stderr)
                match = match[-1:]
        if match:
            line = match[-1]
            flag = " ⚠regression" if line.get("regression") else ""
            out.append(f"| {label} | {fmt(line)}{flag} |")
    return "\n".join(out)


def splice(path, block):
    with open(path) as f:
        text = f.read()
    pat = re.compile(r"<!-- BENCH:BEGIN -->.*?<!-- BENCH:END -->", re.S)
    if not pat.search(text):
        raise SystemExit(f"{path} has no BENCH:BEGIN/END markers")
    text = pat.sub(f"<!-- BENCH:BEGIN -->\n{block}\n<!-- BENCH:END -->", text)
    with open(path, "w") as f:
        f.write(text)
    print(f"updated {path}")


def main():
    if len(sys.argv) > 1:
        artifact = sys.argv[1]
    else:
        arts = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
        if not arts:
            raise SystemExit("no BENCH_r*.json artifact found")
        artifact = arts[-1]
    lines = load(artifact)
    block = render(lines, os.path.basename(artifact))
    splice(os.path.join(ROOT, "README.md"), block)
    splice(os.path.join(ROOT, "PARITY.md"), block)


if __name__ == "__main__":
    main()
