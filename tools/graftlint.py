#!/usr/bin/env python
"""graftlint CLI — the repo's JAX/TPU static-analysis suite.

    python tools/graftlint.py deeplearning4j_tpu            # report
    python tools/graftlint.py --check deeplearning4j_tpu    # exit 1 on findings
    python tools/graftlint.py --check --stage all           # + jaxpr + spmd
    python tools/graftlint.py --check --stage spmd          # SPMD/collectives
    python tools/graftlint.py --json ...                    # machine output
    python tools/graftlint.py --write-baseline ...          # grandfather
    python tools/graftlint.py --update-budget               # refreeze op bounds
    python tools/graftlint.py --update-collectives          # refreeze stage 3
    python tools/graftlint.py --check --stage concurrency   # host threads
    python tools/graftlint.py --update-locks                # refreeze stage 4
    python tools/graftlint.py --check --stage precision     # dtype dataflow
    python tools/graftlint.py --update-precision            # refreeze stage 5
    python tools/graftlint.py --changed                     # diff-scoped fast
    python tools/graftlint.py --rules                       # rule inventory

Stage `ast` (default) is pure stdlib and instant — suitable as a
pre-commit step; it runs all AST rules G001-G034. Stage `jaxpr` traces
the jitted entry points on CPU (~1 min). Stage `spmd` runs the
G010-G013 rules plus the collective-consistency audit
(analysis/collective_audit.py): frozen ordered collective signatures and
the simulated-rank divergence (deadlock) check; pass a fixture .py
defining GRAFTLINT_SPMD_ENTRIES to divergence-check its entries instead
of the built-ins. Stage `concurrency` (pure stdlib, like `ast`) runs
the host-thread rules G025-G028 plus the lock-order audit
(analysis/lock_audit.py): edges frozen in analysis/lock_order.json, a
lock-order CYCLE (D001) always exits 1; pass explicit .py paths to
audit fixtures without the frozen-set comparison. Stage `precision`
runs the dtype-discipline rules G031-G034 plus the precision-flow
audit (analysis/precision_audit.py): per-entry dtype profiles frozen
in analysis/precision_budget.json, sub-f32 accumulation chains (P001),
int8 quantize/dequantize pairing (P002), convert churn (P003),
widening collectives (P004), and rank-divergent profiles (P005, the
C003 deadlock class); pass a fixture .py defining
GRAFTLINT_PRECISION_ENTRIES to profile its entries instead.
`--changed [REF]` scopes the lint to .py files touched since REF
(default HEAD) — the sub-second pre-commit mode. Exit codes: 0 clean,
1 findings (--check) or any D001, 2 usage/env error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

DEFAULT_BASELINE = os.path.join(ROOT, "tools", "graftlint_baseline.json")


def _stub_packages() -> None:
    """Register `deeplearning4j_tpu(.analysis)` as namespace-style stubs
    so `analysis.*` submodules import directly from their files, skipping
    the root __init__'s nn/jax re-exports. All intra-repo imports use
    full dotted paths, so the skipped re-exports are never missed."""
    import types
    pkg = types.ModuleType("deeplearning4j_tpu")
    pkg.__path__ = [os.path.join(ROOT, "deeplearning4j_tpu")]
    sub = types.ModuleType("deeplearning4j_tpu.analysis")
    sub.__path__ = [os.path.join(ROOT, "deeplearning4j_tpu", "analysis")]
    sys.modules.setdefault("deeplearning4j_tpu", pkg)
    sys.modules.setdefault("deeplearning4j_tpu.analysis", sub)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint", description=__doc__)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: deeplearning4j_tpu)")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero when there are non-baselined "
                         "findings")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--stage",
                    choices=("ast", "jaxpr", "spmd", "concurrency",
                             "precision", "all"),
                    default="ast")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather all current AST findings into the "
                         "baseline file")
    ap.add_argument("--update-budget", action="store_true",
                    help="retrace all entry points and refreeze the "
                         "jaxpr op-count budget")
    ap.add_argument("--update-collectives", action="store_true",
                    help="retrace the stage-3 entry points and refreeze "
                         "the ordered collective signatures")
    ap.add_argument("--update-locks", action="store_true",
                    help="rescan the package lock-order graph and "
                         "refreeze the blessed edge set "
                         "(analysis/lock_order.json)")
    ap.add_argument("--update-precision", action="store_true",
                    help="retrace the stage-5 entry points and refreeze "
                         "the per-entry precision manifest "
                         "(analysis/precision_budget.json)")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="lint only .py files touched since REF "
                         "(default HEAD: staged + unstaged + untracked) "
                         "— the sub-second pre-commit mode")
    ap.add_argument("--rules", action="store_true",
                    help="print the per-stage rule inventory and exit")
    args = ap.parse_args(argv)

    if (args.stage in ("ast", "concurrency") or args.rules
            or args.update_locks) and not (args.update_budget
                                           or args.update_collectives
                                           or args.update_precision):
        # Pre-commit path: stub the package parents so the analysis
        # modules load WITHOUT the root __init__ (which imports the full
        # nn stack and jax). Stages 1 and 4 stay pure-stdlib-fast.
        _stub_packages()

    if args.rules:
        return _print_rules()
    from deeplearning4j_tpu.analysis.ast_pass import lint_paths
    from deeplearning4j_tpu.analysis.core import (load_baseline,
                                                  split_baselined,
                                                  write_baseline)

    paths = args.paths or [os.path.join(ROOT, "deeplearning4j_tpu")]
    if args.changed is not None:
        paths = _changed_paths(args.changed)
        if not paths:
            print(f"graftlint: no .py files changed since {args.changed}")
            return 0
    new, old, counts, signatures = [], [], {}, {}
    profiles: dict = {}

    if args.stage in ("ast", "all", "spmd", "concurrency", "precision"):
        findings = lint_paths(paths, root=ROOT)
        if args.stage == "spmd":
            # the SPMD stage lints its own rule family only; G001-G009
            # stay with --stage ast
            from deeplearning4j_tpu.analysis.spmd_rules import \
                SPMD_RULE_IDS
            findings = [f for f in findings if f.rule in SPMD_RULE_IDS]
        elif args.stage == "concurrency":
            from deeplearning4j_tpu.analysis.concurrency_rules import \
                CONC_RULE_IDS
            findings = [f for f in findings if f.rule in CONC_RULE_IDS]
        elif args.stage == "precision":
            from deeplearning4j_tpu.analysis.precision_rules import \
                PRECISION_RULE_IDS
            findings = [f for f in findings
                        if f.rule in PRECISION_RULE_IDS]
        if args.write_baseline:
            write_baseline(args.baseline, findings)
            print(f"baselined {len(findings)} findings -> {args.baseline}")
            return 0
        n, o = split_baselined(findings, load_baseline(args.baseline))
        new.extend(n)
        old.extend(o)

    needs_jax = (args.stage in ("jaxpr", "spmd", "precision", "all")
                 or args.update_budget or args.update_collectives
                 or args.update_precision)
    if needs_jax:
        # CPU-only + virtual devices, matching the tier-1 environment,
        # before any jax backend initialization.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from deeplearning4j_tpu.util.virtual_devices import \
            ensure_cpu_devices
        ensure_cpu_devices(8)

    if args.stage in ("jaxpr", "all") or args.update_budget:
        from deeplearning4j_tpu.analysis import jaxpr_audit
        if args.update_budget:
            _, counts = jaxpr_audit.audit()
            jaxpr_audit.write_budget(counts)
            print(f"froze op budgets for {len(counts)} entry points -> "
                  f"{jaxpr_audit.BUDGET_PATH}")
            for name, count in sorted(counts.items()):
                print(f"  {name}: {count} ops")
            return 0
        jfindings, counts = jaxpr_audit.audit()
        new.extend(jfindings)

    if args.stage in ("spmd", "all") or args.update_collectives:
        from deeplearning4j_tpu.analysis import collective_audit
        if args.update_collectives:
            _, signatures = collective_audit.audit(divergence=False)
            collective_audit.write_budget(signatures)
            print(f"froze collective signatures for {len(signatures)} "
                  f"entry points -> {collective_audit.BUDGET_PATH}")
            for name, sig in sorted(signatures.items()):
                print(f"  {name}: {len(sig)} collective(s)")
            return 0
        # fixture .py paths exposing GRAFTLINT_SPMD_ENTRIES are audited
        # INSTEAD of the built-ins (targeted demo/debug runs); otherwise
        # the frozen entry points get the full budget + divergence pass
        cfindings, signatures = collective_audit.audit_paths(paths)
        if not signatures:
            cfindings, signatures = collective_audit.audit()
        new.extend(cfindings)

    lock_edges: list[str] = []
    if args.stage in ("concurrency", "all") or args.update_locks:
        from deeplearning4j_tpu.analysis import lock_audit
        if args.update_locks:
            edge_strs, _ = lock_audit.current_edges()
            lock_audit.write_locks(edge_strs)
            print(f"froze {len(edge_strs)} lock-order edge(s) -> "
                  f"{lock_audit.LOCKS_PATH}")
            for s in edge_strs:
                print(f"  {s}")
            return 0
        # explicit .py paths are audited as fixtures (no frozen-set
        # comparison); the default package sweep checks for drift
        explicit_py = [p for p in (args.paths or [])
                       if p.endswith(".py")]
        if explicit_py and len(explicit_py) == len(args.paths):
            lfindings, lock_edges = lock_audit.audit_paths(explicit_py)
        else:
            lfindings, lock_edges = lock_audit.audit()
        new.extend(lfindings)

    if args.stage in ("precision", "all") or args.update_precision:
        from deeplearning4j_tpu.analysis import precision_audit
        if args.update_precision:
            _, profiles = precision_audit.audit(divergence=False)
            precision_audit.write_budget(profiles)
            print(f"froze precision profiles for {len(profiles)} entry "
                  f"points -> {precision_audit.BUDGET_PATH}")
            for name, prof in sorted(profiles.items()):
                print(f"  {name}: {sum(prof['dots'].values())} dot(s), "
                      f"{sum(prof['converts'].values())} convert(s), "
                      f"q8 {prof['q8']['quantize']}q/"
                      f"{prof['q8']['dequantize']}dq")
            return 0
        # fixture .py paths exposing GRAFTLINT_PRECISION_ENTRIES are
        # profiled INSTEAD of the built-ins (demo/debug runs); otherwise
        # the frozen entries get the manifest + rank-divergence pass
        pfindings, profiles = precision_audit.audit_paths(paths)
        if not profiles:
            pfindings, profiles = precision_audit.audit()
        new.extend(pfindings)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "grandfathered": [f.to_json() for f in old],
            "jaxpr_op_counts": counts,
            "collective_signatures": signatures,
            "lock_order_edges": lock_edges,
            "precision_profiles": profiles,
        }, indent=1))
    else:
        for f in new:
            print(f.format())
        if old:
            print(f"({len(old)} grandfathered finding(s) in baseline)")
        if counts:
            print(f"jaxpr audit: {len(counts)} entry points traced")
        if signatures:
            print(f"collective audit: {len(signatures)} entry points "
                  "traced")
        if lock_edges:
            print(f"lock-order audit: {len(lock_edges)} edge(s)")
        if profiles:
            print(f"precision audit: {len(profiles)} entry points "
                  "profiled")
        print(f"graftlint: {len(new)} finding(s)")
    # a lock-order cycle is a deadlock waiting for load — never
    # reportable-only, regardless of --check or baseline
    if any(f.rule == "D001" for f in new):
        return 1
    return 1 if (new and args.check) else 0


def _changed_paths(ref: str) -> list[str]:
    """Absolute paths of .py files touched since `ref` (staged +
    unstaged via `git diff`, plus untracked). Exits 2 on a bad ref —
    the usage-error contract."""
    import subprocess

    diff = subprocess.run(["git", "diff", "--name-only", "-z", ref, "--"],
                          cwd=ROOT, capture_output=True, text=True)
    if diff.returncode != 0:
        print(diff.stderr.strip() or f"git diff {ref} failed",
              file=sys.stderr)
        sys.exit(2)
    names = [n for n in diff.stdout.split("\0") if n]
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard", "-z"],
        cwd=ROOT, capture_output=True, text=True)
    if untracked.returncode == 0:
        names += [n for n in untracked.stdout.split("\0") if n]
    return sorted({os.path.join(ROOT, n) for n in names
                   if n.endswith(".py")
                   and os.path.isfile(os.path.join(ROOT, n))})


def _print_rules() -> int:
    """Per-stage rule inventory (from RULE_DOCS + the audit docs)."""
    from deeplearning4j_tpu.analysis.ast_rules import RULE_DOCS
    from deeplearning4j_tpu.analysis.concurrency_rules import \
        CONC_RULE_IDS
    from deeplearning4j_tpu.analysis.lock_audit import \
        RULE_DOCS as LOCK_DOCS
    from deeplearning4j_tpu.analysis.precision_rules import \
        PRECISION_RULE_IDS
    from deeplearning4j_tpu.analysis.spmd_rules import SPMD_RULE_IDS

    # jaxpr/spmd/precision audit rules are documented in their modules'
    # headers; summarized here so --rules covers every id the suite can
    # emit
    audit_docs = {
        "J001": "forbidden primitive (device_put/callback/transfer) in "
                "a jitted entry point",
        "J002": "op count over the frozen jaxpr budget",
        "J003": "float64 value in the traced program",
        "J004": "entry point missing from the budget file",
        "C001": "collective signature drift vs the frozen set",
        "C002": "entry point missing from the frozen signature file",
        "C003": "rank-divergent collective sequence (fleet deadlock)",
        "P001": "sub-f32 accumulation in a reduction chain (scan carry "
                "/ reduce-over-dot / cumulative / psum operand)",
        "P002": "broken int8 quantize<->dequantize pairing (raw-code "
                "read, or requantize without write-head masking)",
        "P003": "convert_element_type round-trip churn (upcast-downcast "
                "ping-pong, intermediate otherwise unused)",
        "P004": "collective operand wider than the entry's floating "
                "inputs (widened bytes on the wire)",
        "P005": "rank-divergent precision profile (fleet deadlock "
                "class)",
        "PB01": "precision profile drift vs the frozen manifest",
    }
    stages = [
        ("ast", sorted(set(RULE_DOCS) - SPMD_RULE_IDS - CONC_RULE_IDS
                       - PRECISION_RULE_IDS)),
        ("jaxpr", ["J001", "J002", "J003", "J004"]),
        ("spmd", sorted(SPMD_RULE_IDS) + ["C001", "C002", "C003"]),
        ("concurrency", sorted(CONC_RULE_IDS) + sorted(LOCK_DOCS)),
        ("precision", sorted(PRECISION_RULE_IDS)
         + ["P001", "P002", "P003", "P004", "P005", "PB01"]),
    ]
    for stage, ids in stages:
        print(f"stage {stage}:")
        for rid in ids:
            doc = RULE_DOCS.get(rid) or audit_docs.get(rid) \
                or LOCK_DOCS.get(rid, "")
            first = doc.split(";")[0].split(" — ")[0].strip()
            print(f"  {rid}  {first}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
