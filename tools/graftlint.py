#!/usr/bin/env python
"""graftlint CLI — the repo's JAX/TPU static-analysis suite.

    python tools/graftlint.py deeplearning4j_tpu            # report
    python tools/graftlint.py --check deeplearning4j_tpu    # exit 1 on findings
    python tools/graftlint.py --check --stage all           # + jaxpr + spmd
    python tools/graftlint.py --check --stage spmd          # SPMD/collectives
    python tools/graftlint.py --json ...                    # machine output
    python tools/graftlint.py --write-baseline ...          # grandfather
    python tools/graftlint.py --update-budget               # refreeze op bounds
    python tools/graftlint.py --update-collectives          # refreeze stage 3

Stage `ast` (default) is pure stdlib and instant — suitable as a
pre-commit step; it runs all AST rules G001-G016. Stage `jaxpr` traces
the jitted entry points on CPU (~1 min). Stage `spmd` runs the
G010-G013 rules plus the collective-consistency audit
(analysis/collective_audit.py): frozen ordered collective signatures and
the simulated-rank divergence (deadlock) check; pass a fixture .py
defining GRAFTLINT_SPMD_ENTRIES to divergence-check its entries instead
of the built-ins. Exit codes: 0 clean, 1 findings (--check), 2
usage/env error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

DEFAULT_BASELINE = os.path.join(ROOT, "tools", "graftlint_baseline.json")


def _stub_packages() -> None:
    """Register `deeplearning4j_tpu(.analysis)` as namespace-style stubs
    so `analysis.*` submodules import directly from their files, skipping
    the root __init__'s nn/jax re-exports. All intra-repo imports use
    full dotted paths, so the skipped re-exports are never missed."""
    import types
    pkg = types.ModuleType("deeplearning4j_tpu")
    pkg.__path__ = [os.path.join(ROOT, "deeplearning4j_tpu")]
    sub = types.ModuleType("deeplearning4j_tpu.analysis")
    sub.__path__ = [os.path.join(ROOT, "deeplearning4j_tpu", "analysis")]
    sys.modules.setdefault("deeplearning4j_tpu", pkg)
    sys.modules.setdefault("deeplearning4j_tpu.analysis", sub)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint", description=__doc__)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: deeplearning4j_tpu)")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero when there are non-baselined "
                         "findings")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--stage", choices=("ast", "jaxpr", "spmd", "all"),
                    default="ast")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather all current AST findings into the "
                         "baseline file")
    ap.add_argument("--update-budget", action="store_true",
                    help="retrace all entry points and refreeze the "
                         "jaxpr op-count budget")
    ap.add_argument("--update-collectives", action="store_true",
                    help="retrace the stage-3 entry points and refreeze "
                         "the ordered collective signatures")
    args = ap.parse_args(argv)

    if args.stage == "ast" and not (args.update_budget
                                    or args.update_collectives):
        # Pre-commit path: stub the package parents so the analysis
        # modules load WITHOUT the root __init__ (which imports the full
        # nn stack and jax). Stage 1 stays pure-stdlib-fast.
        _stub_packages()
    from deeplearning4j_tpu.analysis.ast_pass import lint_paths
    from deeplearning4j_tpu.analysis.core import (load_baseline,
                                                  split_baselined,
                                                  write_baseline)

    paths = args.paths or [os.path.join(ROOT, "deeplearning4j_tpu")]
    new, old, counts, signatures = [], [], {}, {}

    if args.stage in ("ast", "all", "spmd"):
        findings = lint_paths(paths, root=ROOT)
        if args.stage == "spmd":
            # the SPMD stage lints its own rule family only; G001-G009
            # stay with --stage ast
            from deeplearning4j_tpu.analysis.spmd_rules import \
                SPMD_RULE_IDS
            findings = [f for f in findings if f.rule in SPMD_RULE_IDS]
        if args.write_baseline:
            write_baseline(args.baseline, findings)
            print(f"baselined {len(findings)} findings -> {args.baseline}")
            return 0
        n, o = split_baselined(findings, load_baseline(args.baseline))
        new.extend(n)
        old.extend(o)

    needs_jax = (args.stage in ("jaxpr", "spmd", "all")
                 or args.update_budget or args.update_collectives)
    if needs_jax:
        # CPU-only + virtual devices, matching the tier-1 environment,
        # before any jax backend initialization.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from deeplearning4j_tpu.util.virtual_devices import \
            ensure_cpu_devices
        ensure_cpu_devices(8)

    if args.stage in ("jaxpr", "all") or args.update_budget:
        from deeplearning4j_tpu.analysis import jaxpr_audit
        if args.update_budget:
            _, counts = jaxpr_audit.audit()
            jaxpr_audit.write_budget(counts)
            print(f"froze op budgets for {len(counts)} entry points -> "
                  f"{jaxpr_audit.BUDGET_PATH}")
            for name, count in sorted(counts.items()):
                print(f"  {name}: {count} ops")
            return 0
        jfindings, counts = jaxpr_audit.audit()
        new.extend(jfindings)

    if args.stage in ("spmd", "all") or args.update_collectives:
        from deeplearning4j_tpu.analysis import collective_audit
        if args.update_collectives:
            _, signatures = collective_audit.audit(divergence=False)
            collective_audit.write_budget(signatures)
            print(f"froze collective signatures for {len(signatures)} "
                  f"entry points -> {collective_audit.BUDGET_PATH}")
            for name, sig in sorted(signatures.items()):
                print(f"  {name}: {len(sig)} collective(s)")
            return 0
        # fixture .py paths exposing GRAFTLINT_SPMD_ENTRIES are audited
        # INSTEAD of the built-ins (targeted demo/debug runs); otherwise
        # the frozen entry points get the full budget + divergence pass
        cfindings, signatures = collective_audit.audit_paths(paths)
        if not signatures:
            cfindings, signatures = collective_audit.audit()
        new.extend(cfindings)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "grandfathered": [f.to_json() for f in old],
            "jaxpr_op_counts": counts,
            "collective_signatures": signatures,
        }, indent=1))
    else:
        for f in new:
            print(f.format())
        if old:
            print(f"({len(old)} grandfathered finding(s) in baseline)")
        if counts:
            print(f"jaxpr audit: {len(counts)} entry points traced")
        if signatures:
            print(f"collective audit: {len(signatures)} entry points "
                  "traced")
        print(f"graftlint: {len(new)} finding(s)")
    return 1 if (new and args.check) else 0


if __name__ == "__main__":
    sys.exit(main())
