#!/usr/bin/env python
"""kerneltune — the Pallas-kernel micro-bench sweep behind
deeplearning4j_tpu/ops/tuning_table.json.

    python tools/kerneltune.py                      # default sweep -> table
    python tools/kerneltune.py --quick              # tiny shapes (CI smoke)
    python tools/kerneltune.py --dry-run            # list configs, no timing
    python tools/kerneltune.py --configs flash_fwd flash_bwd
    python tools/kerneltune.py --out /tmp/table.json --repeats 5

For every swept config key ``(kernel, T, D, causal, dropout, masked)``
the harness times the DEFAULT heuristic blocks and every structurally
valid candidate variant through the real dispatch (``autotune.override``
forces the candidate; the kernels themselves decide single-block vs
streaming, fused vs two-kernel backward, exactly as in training). The
written entry is the fastest candidate only when it beats the default by
``--margin`` (3% by default) — otherwise the default params are recorded
with both timings, so **every table entry matches-or-beats the default
heuristics in this harness's own micro-bench** by construction.
``tools/benchdiff.py old_table new_table`` names changed entries and
flags timing regressions.

Every measurement emits a typed ``kernel_tune`` telemetry event
(telemetry/recorder.py) when ``DL4J_TPU_TELEMETRY`` (or ``--telemetry``)
names a log, so the provenance trail survives a crashed sweep.

Off-TPU the kernels run in interpret mode: candidate timings are real
but measure the CPU emulator, not the MXU, so by default an off-TPU
sweep times every candidate (telemetry + report) but RECORDS the default
params — a CPU artifact (e.g. "G=1 beats G=8", true only because
interpret G-batching is a python loop) must never displace a
TPU-measured default in the checked-in table. ``--trust-interpret``
lifts that for targeted experiments. The authoritative sweep runs on the
TPU driver and refreshes the table deliberately (the sweep -> freeze ->
gate workflow, ARCHITECTURE §Kernel autotuning).
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

DEFAULT_MARGIN = 0.03


def _stub_packages() -> None:
    """Load ops/util/telemetry submodules without the package root's
    full nn/jax re-export stack (the graftlint/benchdiff stub idiom); a
    fully imported real package is left alone."""
    import types
    for name in ("deeplearning4j_tpu", "deeplearning4j_tpu.ops",
                 "deeplearning4j_tpu.util", "deeplearning4j_tpu.telemetry"):
        if name not in sys.modules:
            mod = types.ModuleType(name)
            mod.__path__ = [os.path.join(ROOT, *name.split("."))]
            sys.modules[name] = mod


# ------------------------------------------------------------- sweep plan

def sweep_configs(quick: bool) -> list[dict]:
    """The default config list: the bench flagship shapes (quick mode
    shrinks T/batch so CI smoke runs finish in seconds)."""
    if quick:
        flash_shapes = [
            dict(B=2, H=2, T=256, D=32, causal=True, dropout=False,
                 masked=False),
            dict(B=2, H=2, T=256, D=32, causal=True, dropout=True,
                 masked=False),
        ]
        xent = [dict(N=256, d=128, V=2560)]
        ln = [dict(N=512, C=256)]
        decode = [dict(B=4, H=2, S=256, D=32, page=16)]
        decode_q8 = [dict(B=4, H=2, S=256, D=32, page=16)]
        sample = [dict(B=128, V=2048)]
        neg_softmax = [dict(B=256, K=5, D=128)]
    else:
        flash_shapes = [
            # the T=512 flagship (transformer mode, D=64 head pairs)
            dict(B=4, H=4, T=512, D=64, causal=True, dropout=False,
                 masked=False),
            dict(B=4, H=4, T=512, D=64, causal=True, dropout=True,
                 masked=False),
            dict(B=4, H=4, T=512, D=64, causal=True, dropout=False,
                 masked=True),
            # the D=128 packed-qkv regime
            dict(B=2, H=2, T=512, D=128, causal=True, dropout=False,
                 masked=False),
            # the longcontext mode's per-tile shape
            dict(B=1, H=2, T=1024, D=64, causal=True, dropout=False,
                 masked=False),
        ]
        xent = [dict(N=2048, d=256, V=10240)]
        ln = [dict(N=2048, C=512)]
        decode = [
            # serving decode-step shapes: slots x heads single-query
            # against a page-quantized cache (serving/kvcache.py grid)
            dict(B=8, H=4, S=1024, D=64, page=16),
            dict(B=8, H=2, S=2048, D=128, page=16),
        ]
        decode_q8 = [
            # same serving grid, int8 pages: block_k candidates stay
            # page multiples so no block splits a scale page
            dict(B=8, H=4, S=1024, D=64, page=16),
            dict(B=8, H=2, S=2048, D=128, page=16),
        ]
        sample = [
            # fused sampling: slots x vocab logit rows per decode step
            dict(B=256, V=8192),
            dict(B=256, V=32768),
        ]
        neg_softmax = [
            # embedding-engine SGNS step shapes: pair-batch rows x
            # negatives x vector length (embedding/engine.py)
            dict(B=1024, K=5, D=128),
            dict(B=2048, K=10, D=128),
        ]
    out = []
    for s in flash_shapes:
        out.append(dict(family="flash_fwd", **s))
        out.append(dict(family="flash_bwd", **s))
    for s in ln:
        out.append(dict(family="fused_layer_norm", **s))
    for s in xent:
        out.append(dict(family="softmax_xent", **s))
    for s in decode:
        out.append(dict(family="decode_attn", **s))
    for s in decode_q8:
        out.append(dict(family="decode_attn_q8", **s))
    for s in sample:
        out.append(dict(family="sample", **s))
    for s in neg_softmax:
        out.append(dict(family="neg_softmax", **s))
    return out


def _pow2_blocks(T: int) -> list[int]:
    from deeplearning4j_tpu.ops import autotune
    b, out = autotune.BLOCK, []
    while b <= T and T % b == 0:
        out.append(b)
        b *= 2
    return out


def candidates(cfg: dict) -> list[dict]:
    """Structurally valid param variants for one config (the default
    heuristic's pick is timed separately and excluded here)."""
    from deeplearning4j_tpu.ops import autotune
    fam = cfg["family"]
    outs: list[dict] = []
    if fam in ("flash_fwd", "flash_bwd"):
        T, BH = cfg["T"], cfg["B"] * cfg["H"]
        blocks = _pow2_blocks(T)
        gs = [g for g in (1, 2, 4, 8) if BH % g == 0]
        for bq, bk in itertools.product(blocks, blocks):
            # G-batching only exists in the single-block regime
            for g in (gs if bq == T and bk == T else [1]):
                outs.append({"block_q": bq, "block_k": bk, "g": g})
    elif fam == "fused_layer_norm":
        N = cfg["N"]
        for bn in (128, 256, 512, 1024):
            if N % bn == 0 or bn == N:
                outs.append({"rows": bn})
    elif fam == "softmax_xent":
        for bn, bv in itertools.product((256, 512, 1024, 2048),
                                        (1024, 2048, 4096)):
            outs.append({"block_n": bn, "block_v": bv})
    elif fam in ("decode_attn", "decode_attn_q8"):
        # block_k over pages: page-multiple divisors of the quantized
        # cache capacity (the only blocks the serving grid ever needs;
        # the q8 variant additionally may not split a scale page, which
        # page-multiple candidates satisfy by construction)
        S, page = cfg["S"], cfg["page"]
        bk = page
        while bk <= S:
            if S % bk == 0:
                outs.append({"block_k": bk})
            bk *= 2
    elif fam == "sample":
        # row blocks: divisors of the batch that are lane-tile
        # multiples (or the whole batch) — the sample_rows legality rule
        B = cfg["B"]
        bn = 8
        while bn <= B:
            if B % bn == 0 and (bn % autotune.LANES == 0 or bn == B):
                outs.append({"rows": bn})
            bn *= 2
    elif fam == "neg_softmax":
        # same row-block legality as sample: divisors of the pair-batch
        # that are lane multiples (or the whole batch)
        B = cfg["B"]
        bn = 8
        while bn <= B:
            if B % bn == 0 and (bn % autotune.LANES == 0 or bn == B):
                outs.append({"rows": bn})
            bn *= 2
    else:
        raise KeyError(fam)
    default = default_params(cfg)
    return [c for c in outs if c != default]


def config_key(cfg: dict) -> str:
    from deeplearning4j_tpu.ops import autotune
    fam = cfg["family"]
    if fam in ("flash_fwd", "flash_bwd"):
        return autotune.config_key(fam, cfg["T"], cfg["D"],
                                   causal=cfg["causal"],
                                   dropout=cfg["dropout"],
                                   masked=cfg["masked"])
    if fam == "fused_layer_norm":
        return autotune.config_key(fam, cfg["N"], cfg["C"])
    if fam == "softmax_xent":
        return autotune.config_key(fam, cfg["V"], cfg["d"])
    if fam in ("decode_attn", "decode_attn_q8"):
        return autotune.config_key(fam, cfg["S"], cfg["D"])
    if fam == "sample":
        return autotune.config_key(fam, cfg["B"], cfg["V"])
    if fam == "neg_softmax":
        return autotune.config_key(fam, cfg["B"], cfg["D"])
    raise KeyError(fam)


def default_params(cfg: dict) -> dict:
    """What the deterministic heuristics pick for this config — the
    baseline every candidate must beat (resolved with the table and
    overrides FORCED OFF so a previous sweep cannot shift the
    baseline)."""
    from deeplearning4j_tpu.ops import autotune
    fam = cfg["family"]
    prev = os.environ.get(autotune.ENV_TUNING)
    os.environ[autotune.ENV_TUNING] = "off"
    try:
        if fam in ("flash_fwd", "flash_bwd"):
            bq, bk = autotune.flash_blocks(
                cfg["T"], cfg["D"], causal=cfg["causal"],
                dropout=cfg["dropout"], masked=cfg["masked"], kernel=fam)
            import jax.numpy as jnp  # noqa: F401  (jax initialized)
            from deeplearning4j_tpu.ops import flash_attention as fa
            BH, T, D = cfg["B"] * cfg["H"], cfg["T"], cfg["D"]
            extra = int(T * T * 4) if cfg["dropout"] else 0
            sl = (fa._fwd_slice_bytes(T, D) if fam == "flash_fwd"
                  else fa._bwd_slice_bytes(T, D)) + extra
            g = (fa._pick_g(BH, T, D, sl)
                 if bq == T and bk == T else 1)
            return {"block_q": bq, "block_k": bk, "g": g}
        if fam == "fused_layer_norm":
            return {"rows": autotune.ln_rows(cfg["N"], cfg["C"])}
        if fam == "softmax_xent":
            bn, bv = autotune.xent_blocks(cfg["N"], cfg["d"], cfg["V"])
            return {"block_n": bn, "block_v": bv}
        if fam == "decode_attn":
            return {"block_k": autotune.decode_block(cfg["S"], cfg["D"])}
        if fam == "decode_attn_q8":
            return {"block_k": autotune.decode_block_q8(
                cfg["S"], cfg["D"], cfg["page"])}
        if fam == "sample":
            return {"rows": autotune.sample_rows(cfg["B"], cfg["V"])}
        if fam == "neg_softmax":
            return {"rows": autotune.neg_softmax_rows(cfg["B"], cfg["D"])}
    finally:
        if prev is None:
            os.environ.pop(autotune.ENV_TUNING, None)
        else:
            os.environ[autotune.ENV_TUNING] = prev
    raise KeyError(fam)


# ---------------------------------------------------------------- timing

def _build_call(cfg: dict):
    """-> zero-arg callable running one kernel invocation (jitted; built
    fresh per candidate so each variant gets its own compile)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    fam = cfg["family"]
    rng = np.random.default_rng(0)

    if fam in ("flash_fwd", "flash_bwd"):
        from deeplearning4j_tpu.ops.flash_attention import flash_attention
        B, H, T, D = cfg["B"], cfg["H"], cfg["T"], cfg["D"]
        q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)) * 0.2,
                               jnp.float32) for _ in range(3))
        kw = dict(causal=cfg["causal"])
        if cfg["masked"]:
            kw["mask"] = jnp.asarray(rng.random((B, T)) > 0.1, jnp.float32)
        if cfg["dropout"]:
            kw["dropout"] = 0.1
            kw["dropout_rng"] = jax.random.PRNGKey(0)
        if fam == "flash_fwd":
            f = jax.jit(lambda q, k, v: flash_attention(q, k, v, **kw))
        else:
            f = jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(flash_attention(q, k, v, **kw)),
                argnums=(0, 1, 2)))
        return lambda: f(q, k, v)

    if fam == "fused_layer_norm":
        from deeplearning4j_tpu.ops.fused_layernorm import fused_layer_norm
        N, C = cfg["N"], cfg["C"]
        x = jnp.asarray(rng.standard_normal((N, C)), jnp.float32)
        g = jnp.ones((C,), jnp.float32)
        b = jnp.zeros((C,), jnp.float32)
        f = jax.jit(jax.grad(
            lambda x, g, b: jnp.sum(fused_layer_norm(x, g, b) ** 2),
            argnums=(0, 1, 2)))
        return lambda: f(x, g, b)

    if fam == "decode_attn":
        from deeplearning4j_tpu.ops.decode_attention import decode_attention
        B, H, S, D = cfg["B"], cfg["H"], cfg["S"], cfg["D"]
        q = jnp.asarray(rng.standard_normal((B, H, D)) * 0.2, jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, S, H, D)) * 0.2,
                        jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, S, H, D)) * 0.2,
                        jnp.float32)
        # mixed fill depths, like a continuous batch mid-flight
        pos = jnp.asarray(rng.integers(0, S, (B,)), jnp.int32)
        f = jax.jit(lambda q, k, v, pos: decode_attention(q, k, v, pos))
        return lambda: f(q, k, v, pos)

    if fam == "decode_attn_q8":
        from deeplearning4j_tpu.ops.decode_attention import (
            cache_attention_q8,
            quantize_pages,
        )
        B, H, S, D = cfg["B"], cfg["H"], cfg["S"], cfg["D"]
        page = cfg["page"]
        q = jnp.asarray(rng.standard_normal((B, H, 1, D)) * 0.2,
                        jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, S, H, D)) * 0.2,
                        jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, S, H, D)) * 0.2,
                        jnp.float32)
        kc, ks = quantize_pages(k, page)
        vc, vs = quantize_pages(v, page)
        limit = jnp.asarray(rng.integers(1, S + 1, (B, 1)), jnp.int32)
        f = jax.jit(lambda q, kc, vc, ks, vs, limit: cache_attention_q8(
            q, kc, vc, ks, vs, limit, page))
        return lambda: f(q, kc, vc, ks, vs, limit)

    if fam == "sample":
        from deeplearning4j_tpu.ops import fused_sampling
        B, V = cfg["B"], cfg["V"]
        logits = jnp.asarray(rng.standard_normal((B, V)), jnp.float32)
        noise = fused_sampling.gumbel_noise(jax.random.PRNGKey(0), B, V)
        f = jax.jit(lambda lg, nz: fused_sampling.fused_sample(
            lg, nz, temperature=1.0, top_k=64, top_p=0.9))
        return lambda: f(logits, noise)

    if fam == "neg_softmax":
        from deeplearning4j_tpu.ops.fused_neg_softmax import (
            neg_softmax_scores,
        )
        B, K, D = cfg["B"], cfg["K"], cfg["D"]
        c = jnp.asarray(rng.standard_normal((B, D)) * 0.2, jnp.float32)
        pos = jnp.asarray(rng.standard_normal((B, D)) * 0.2, jnp.float32)
        neg = jnp.asarray(rng.standard_normal((B, K, D)) * 0.2,
                          jnp.float32)
        f = jax.jit(lambda c, pos, neg: neg_softmax_scores(c, pos, neg))
        return lambda: f(c, pos, neg)

    if fam == "softmax_xent":
        from deeplearning4j_tpu.ops.fused_softmax_xent import (
            softmax_xent_head,
        )
        N, d, V = cfg["N"], cfg["d"], cfg["V"]
        x = jnp.asarray(rng.standard_normal((N, d)) * 0.1, jnp.float32)
        w = jnp.asarray(rng.standard_normal((d, V)) * 0.05, jnp.float32)
        b = jnp.zeros((V,), jnp.float32)
        lab = jnp.asarray(rng.integers(0, V, (N,)), jnp.int32)
        f = jax.jit(jax.grad(
            lambda x, w, b: jnp.sum(softmax_xent_head(x, w, b, lab)),
            argnums=(0, 1, 2)))
        return lambda: f(x, w, b)

    raise KeyError(fam)


def time_variant(cfg: dict, params: dict, repeats: int) -> float:
    """Min-of-repeats wall clock of one kernel call with `params` forced
    through the tuning layer. The jitted callable is built INSIDE the
    override so the candidate is baked in at trace time."""
    import jax
    from deeplearning4j_tpu.ops import autotune
    with autotune.override({cfg["family"]: params}):
        call = _build_call(cfg)
        jax.block_until_ready(call())  # compile
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(call())
            best = min(best, time.perf_counter() - t0)
    return best


# ----------------------------------------------------------------- sweep

def sweep(configs: list[dict], repeats: int, margin: float, recorder,
          trust_wins: bool = True) -> dict:
    """Time default + candidates per config -> {key: entry}. With
    trust_wins=False (the off-TPU default) candidates are timed and
    logged but the entry records the default params."""
    entries: dict[str, dict] = {}
    for cfg in configs:
        key = config_key(cfg)
        dflt = default_params(cfg)
        t_dflt = time_variant(cfg, dflt, repeats)
        recorder.kernel_tune(cfg["family"], key, dflt, seconds=t_dflt,
                             role="default")
        best_params, t_best = dflt, t_dflt
        n_cand = 0
        for cand in candidates(cfg):
            t = time_variant(cfg, cand, repeats)
            recorder.kernel_tune(cfg["family"], key, cand, seconds=t,
                                 role="candidate")
            n_cand += 1
            if t < t_best:
                best_params, t_best = cand, t
        # match-or-beat contract: only a decisive win displaces the
        # deterministic default; ties and noise keep the default params
        if best_params is not dflt and t_best >= t_dflt * (1.0 - margin):
            best_params, t_best = dflt, t_dflt
        if best_params is not dflt and not trust_wins:
            print(f"{key}: interpret-mode winner {best_params} "
                  f"({t_best * 1e6:.0f}us vs default "
                  f"{t_dflt * 1e6:.0f}us) NOT recorded — CPU emulator "
                  "timings don't transfer to the MXU "
                  "(--trust-interpret to force)")
            best_params, t_best = dflt, t_dflt
        entry = dict(best_params)
        entry["best_us"] = int(round(t_best * 1e6))
        entry["default_us"] = int(round(t_dflt * 1e6))
        entry["candidates"] = n_cand
        entries[key] = entry
        recorder.kernel_tune(cfg["family"], key, best_params,
                             seconds=t_best, role="chosen",
                             default_seconds=round(t_dflt, 9))
        won = "tuned" if best_params != dflt else "default"
        print(f"{key}: {won} {best_params}  best={t_best * 1e6:.0f}us "
              f"default={t_dflt * 1e6:.0f}us ({n_cand} candidates)")
    return entries


def provenance(repeats: int, margin: float) -> dict:
    import jax
    dev = jax.devices()[0]
    return {
        "tool": "tools/kerneltune.py",
        "date": time.strftime("%Y-%m-%d"),
        "backend": jax.default_backend(),
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "jax": jax.__version__,
        "repeats": repeats,
        "margin": margin,
        "interpret": jax.default_backend() != "tpu",
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kerneltune", description=__doc__)
    ap.add_argument("--out", default=None,
                    help="table path (default: the checked-in "
                         "deeplearning4j_tpu/ops/tuning_table.json)")
    ap.add_argument("--quick", action="store_true",
                    help="tiny shapes — CI smoke, seconds not minutes")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--margin", type=float, default=DEFAULT_MARGIN,
                    help="relative win a candidate needs to displace the "
                         f"default (default {DEFAULT_MARGIN})")
    ap.add_argument("--configs", nargs="*", default=None,
                    help="restrict to these kernel families")
    ap.add_argument("--merge", action="store_true",
                    help="update swept keys in an existing table instead "
                         "of replacing it")
    ap.add_argument("--dry-run", action="store_true")
    ap.add_argument("--trust-interpret", action="store_true",
                    help="let interpret-mode (off-TPU) wins displace the "
                         "defaults in the written table")
    ap.add_argument("--telemetry", default=None,
                    help="telemetry JSONL path (else DL4J_TPU_TELEMETRY)")
    args = ap.parse_args(argv)

    _stub_packages()
    from deeplearning4j_tpu.ops import autotune

    configs = sweep_configs(args.quick)
    if args.configs:
        configs = [c for c in configs if c["family"] in args.configs]
        if not configs:
            print(f"kerneltune: no configs match {args.configs}",
                  file=sys.stderr)
            return 2
    if args.dry_run:
        for cfg in configs:
            print(f"{config_key(cfg)}: default {default_params(cfg)}, "
                  f"{len(candidates(cfg))} candidates")
        return 0

    from deeplearning4j_tpu.telemetry.recorder import Recorder, get_default
    rec = Recorder(args.telemetry) if args.telemetry else get_default()
    rec.meta(role="kerneltune", quick=args.quick, repeats=args.repeats)

    import jax
    trust = jax.default_backend() == "tpu" or args.trust_interpret
    entries = sweep(configs, args.repeats, args.margin, rec,
                    trust_wins=trust)

    out_path = args.out or autotune.TABLE_PATH
    table = {"version": autotune.SCHEMA_VERSION,
             "provenance": provenance(args.repeats, args.margin),
             "entries": entries}
    if args.merge and os.path.exists(out_path):
        with open(out_path) as fh:
            old = json.load(fh)
        merged = dict(old.get("entries", {}))
        merged.update(entries)
        table["entries"] = merged
    problems = autotune.validate_table(table)
    if problems:
        print("kerneltune: refusing to write invalid table:\n  "
              + "\n  ".join(problems), file=sys.stderr)
        return 2
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(table, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, out_path)
    print(f"wrote {len(table['entries'])} entries -> {out_path}")
    rec.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
