#!/usr/bin/env python
"""trafficreplay — the continuous-batching serving bench.

    python tools/trafficreplay.py                      # tiny-LM replay
    python tools/trafficreplay.py --model mlp --requests 200
    python tools/trafficreplay.py --artifact SERVE_r01.json
    python tools/trafficreplay.py --checkpoint ckpt_dir  # serve a real net
    python tools/trafficreplay.py --generate --artifact SERVE_r02.json
    python tools/trafficreplay.py --generate --prompt-lens 8,32 \
        --output-lens 4,16 --slots 4                   # generation replay
    python tools/trafficreplay.py --chaos r0:kill@batch4  # self-healing
    python tools/trafficreplay.py --fleet --artifact SERVE_r03.json

Replays a SEEDED mixed-length / bursty request trace against a freshly
started serving stack (engine + HTTP front door, serving/), drains, and
reports sustained QPS plus p50/p99 latency reconstructed from the
telemetry `request` events ALONE — the JSONL log, not any in-process
timer, is the source of truth, so the same numbers rebuild from the
artifact after a crash or a stdout truncation.

`--generate` replays the AUTOREGRESSIVE trace instead (serving/
GenerationEngine: prefill/decode split over the paged KV cache): a
seeded prompt-length x output-length mix streamed through POST
/generate, with headline tokens/sec (higher-is-better), time-to-first-
token p50/p99 and peak cache-page occupancy (both lower-is-better —
benchdiff inverts), and the same zero-retrace row.

`--chaos SPEC` injects replica-scoped faults (the distributed/faults.py
grammar: `r0:kill@batch4`, `r1:hang@batch2`, `;`-joined) into the
replay's serving replicas, with a live FleetSupervisor healing them —
the self-healing smoke run. `--fleet` runs the ZERO-DOWNTIME OPERATIONS
bench instead (serving/fleet.py): the same bursty trace through a
fixed-replica baseline arm and an autoscaling arm that also absorbs a
replica kill and a mid-traffic weight hot-swap; the SERVE_r03-shaped
artifact adds `swap_ms`, `respawn_ms`, `failed_requests`, and autoscale
occupancy rows (all lower-is-better).

Output: one JSON metric line per number (the bench.py idiom) ending
with the gate-carrying summary line; `--artifact` also writes them as a
SERVE_r*.json file that tools/benchdiff.py diffs across rounds
(latency and retrace lines carry `lower_is_better: true` — benchdiff
inverts its regression direction for them; QPS stays higher-is-better).
Exit code 0 unless the replay could not run at all; regression gating
happens in benchdiff, off the artifacts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trafficreplay", description=__doc__)
    ap.add_argument("--model", choices=("lm", "mlp"), default="lm",
                    help="tiny transformer LM (mixed-length sequences; "
                         "default) or fixed-shape MLP")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--requests", type=int, default=60)
    ap.add_argument("--burst", type=int, default=4,
                    help="requests per arrival burst")
    ap.add_argument("--mean-gap-ms", type=float, default=2.0,
                    help="mean inter-burst gap (the trace's rate knob)")
    ap.add_argument("--lens", default="8,16,32",
                    help="comma list of request sequence lengths "
                         "(lm model; also the seq bucket lattice)")
    ap.add_argument("--buckets", default="1,2,4",
                    help="comma list of batch-size buckets")
    ap.add_argument("--max-wait-ms", type=float, default=4.0)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--checkpoint", default=None,
                    help="Orbax host-checkpoint dir to resume the net "
                         "from before serving")
    ap.add_argument("--artifact", default=None,
                    help="write the SERVE artifact (metric lines + "
                         "summary) here")
    ap.add_argument("--telemetry", default=None,
                    help="telemetry JSONL path (default: a temp file; "
                         "the scoreboard is reconstructed from it)")
    ap.add_argument("--generate", action="store_true",
                    help="replay the autoregressive generation trace "
                         "(prefill/decode split, paged KV cache) "
                         "instead of one-shot predict")
    ap.add_argument("--prompt-lens", default="8,16,32",
                    help="generation trace prompt lengths (also the "
                         "prefill bucket lattice)")
    ap.add_argument("--output-lens", default="4,8,16",
                    help="generation trace output-token budgets")
    ap.add_argument("--slots", type=int, default=4,
                    help="decode slots per generation replica")
    ap.add_argument("--page-size", type=int, default=16,
                    help="KV-cache page size (tokens per page)")
    ap.add_argument("--speculative-k", type=int, default=0,
                    help="speculative decode window width for "
                         "--generate (0 = off; >= 2 drafts k-1 tokens "
                         "per slot and verifies the window in one step)")
    ap.add_argument("--kv-dtype", choices=("f32", "int8"), default="f32",
                    help="KV-cache storage dtype for --generate "
                         "(int8 = per-page-scale quantized pages)")
    ap.add_argument("--chaos", default=None, metavar="SPEC",
                    help="replica-scoped fault spec(s) to inject "
                         "(distributed/faults.py grammar, e.g. "
                         "'r0:kill@batch4'); a FleetSupervisor heals "
                         "them live during the replay")
    ap.add_argument("--fleet", action="store_true",
                    help="run the zero-downtime fleet-operations bench "
                         "(fixed vs autoscaling arm, replica-kill chaos "
                         "+ mid-traffic hot-swap; SERVE_r03 artifact)")
    ap.add_argument("--autoscale-max", type=int, default=3,
                    help="autoscaling arm's replica ceiling (--fleet)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from deeplearning4j_tpu.serving.replay import (run_fleet_replay,
                                                   run_generation_replay,
                                                   run_replay)

    tpath = args.telemetry or os.path.join(
        tempfile.mkdtemp(prefix="trafficreplay_"), "telemetry.jsonl")
    if args.fleet:
        scoreboard = run_fleet_replay(
            seed=args.seed, n_requests=args.requests, burst=args.burst,
            mean_gap_s=args.mean_gap_ms / 1000.0,
            batch_sizes=tuple(int(b) for b in args.buckets.split(",")),
            max_wait_ms=args.max_wait_ms,
            autoscale_max=args.autoscale_max,
            chaos=args.chaos or "r0:kill@batch4",
            telemetry_path=tpath, artifact_path=args.artifact,
            emit=lambda line: print(json.dumps(line), flush=True))
    elif args.generate:
        scoreboard = run_generation_replay(
            seed=args.seed, n_requests=args.requests, burst=args.burst,
            mean_gap_s=args.mean_gap_ms / 1000.0,
            prompt_lengths=tuple(int(t)
                                 for t in args.prompt_lens.split(",")),
            output_lengths=tuple(int(t)
                                 for t in args.output_lens.split(",")),
            slots=args.slots, page_size=args.page_size,
            speculative_k=args.speculative_k, kv_dtype=args.kv_dtype,
            replicas=args.replicas, telemetry_path=tpath,
            artifact_path=args.artifact, checkpoint=args.checkpoint,
            emit=lambda line: print(json.dumps(line), flush=True))
    else:
        scoreboard = run_replay(
            model=args.model, seed=args.seed, n_requests=args.requests,
            burst=args.burst, mean_gap_s=args.mean_gap_ms / 1000.0,
            lengths=tuple(int(t) for t in args.lens.split(",")),
            batch_sizes=tuple(int(b) for b in args.buckets.split(",")),
            max_wait_ms=args.max_wait_ms, replicas=args.replicas,
            telemetry_path=tpath, artifact_path=args.artifact,
            checkpoint=args.checkpoint, chaos=args.chaos,
            emit=lambda line: print(json.dumps(line), flush=True))
    from deeplearning4j_tpu.telemetry.artifact import build_summary

    summary = build_summary(scoreboard["lines"])
    summary["telemetry"] = tpath
    print(json.dumps(summary), flush=True)
    if scoreboard["n_ok"] == 0:
        sys.stderr.write("trafficreplay: no request completed\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
