"""Benchmark harness — prints ONE JSON line per BASELINE.json metric.

Covers all five BASELINE.json configs (BASELINE.md):
  1. lenet       — LeNet-5/MNIST images/sec/chip through the fit-path step
  2. vgg16       — VGG-16/CIFAR-10 images/sec/chip (DAG API)
  3. word2vec    — skip-gram negative sampling words/sec (text8-like corpus)
  4. resnet_dp   — ResNet-20 allreduce-DP vs parameter-averaging speedup
                   (virtual 8-device CPU mesh; ICI analogue of BASELINE #4)
  5. transformer — 6-layer Transformer-LM step time -> tokens/sec + MFU
                   (north star: >=30% MFU)

`python bench.py` runs every mode, each in its own subprocess so jax
backend/platform choices stay isolated (resnet_dp forces the virtual CPU
mesh; the rest use the default backend — the real TPU chip under the
driver). `python bench.py <mode>` runs one mode inline.

The reference publishes no numbers (BASELINE.md), so each `vs_baseline` is
the ratio against the nominal anchor constants below; anchors are re-based
to the first real-TPU measurements as rounds land them.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# Nominal anchors (regression guards; re-based once real-TPU numbers land).
TARGETS = {
    "lenet": 20000.0,        # images/sec/chip
    "vgg16": 2000.0,         # images/sec/chip
    "word2vec": 100000.0,    # words/sec
    "resnet_dp": 1.0,        # allreduce/param-avg speedup (>=1 expected)
    "transformer": 0.30,     # MFU fraction (north star >=30%)
}

# Peak dense bf16 FLOP/s per chip by TPU generation (public spec sheets);
# used only for the MFU denominator.
PEAK_BF16_FLOPS = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5lite", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _peak_flops(device):
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in PEAK_BF16_FLOPS:
        if key in kind:
            return peak
    return None


def _emit(mode: str, value: float, unit: str, **extra) -> None:
    line = {
        "metric": mode if "metric" not in extra else extra.pop("metric"),
        "value": round(float(value), 4),
        "unit": unit,
        "vs_baseline": round(float(value) / TARGETS[mode], 4),
    }
    line.update(extra)
    print(json.dumps(line), flush=True)


def _sync(carry) -> float:
    """Force execution of the whole chained computation by pulling one
    scalar of the final state to host (block_until_ready is not reliable
    over the remote-device tunnel, a host readback is)."""
    import jax
    import jax.numpy as jnp

    leaf = jax.tree.leaves(carry)[0]
    return float(jnp.ravel(leaf.astype(jnp.float32))[0])


def _time_steps(step, args_fn, warmup: int, steps: int) -> float:
    """Seconds/step via a two-point measurement: run `steps` and `3*steps`
    chained iterations, each ended by a scalar host readback, and take the
    slope — this cancels the fixed dispatch/readback round-trip latency
    (~60-100ms through the driver's device tunnel) that would otherwise
    dominate short runs."""

    def timed(n) -> float:
        carry = None
        t0 = time.perf_counter()
        for _ in range(n):
            carry = step(*args_fn(carry))
        _sync(carry)
        return time.perf_counter() - t0

    timed(warmup)  # compile + warm caches (result discarded)
    t1 = timed(steps)
    t3 = timed(3 * steps)
    return max((t3 - t1) / (2 * steps), 1e-9)


def _net_stepper(net, batch):
    """Adapt a network's jitted train step to the _time_steps carry protocol."""
    import jax

    import jax.numpy as jnp

    step = net._get_train_step()

    def args_fn(carry):
        if carry is None:
            # fresh on-device copies: the step donates its buffers, so each
            # timed run must start from un-donated state
            carry = (jax.tree.map(jnp.copy, net.params),
                     jax.tree.map(jnp.copy, net.opt_state),
                     jax.tree.map(jnp.copy, net.state),
                     jax.random.PRNGKey(0))
        params, opt_state, state, key = carry
        key, k = jax.random.split(key)
        return params, opt_state, state, k, key

    def stepper(params, opt_state, state, k, key):
        params, opt_state, state, loss, _ = step(params, opt_state, state, k,
                                                 batch)
        return params, opt_state, state, key

    return stepper, args_fn


# --------------------------------------------------------------------- modes

def bench_lenet() -> None:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.lenet import lenet5

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    batch = 512
    net = lenet5(dtype="bfloat16" if on_tpu else "float32")
    net.init()
    rng = np.random.default_rng(0)
    x = rng.random((batch, 28, 28, 1), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    b = {"features": jnp.asarray(x), "labels": jnp.asarray(y)}
    stepper, args_fn = _net_stepper(net, b)
    sec = _time_steps(stepper, args_fn, warmup=5, steps=30)
    _emit("lenet", batch / sec, "images/sec/chip",
          metric=f"lenet_mnist_images_per_sec_{backend}")


def bench_vgg16() -> None:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.vgg import vgg16

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    batch = 256 if on_tpu else 16
    steps = 20 if on_tpu else 3
    net = vgg16(dtype="bfloat16" if on_tpu else "float32")
    net.init()
    rng = np.random.default_rng(0)
    x = rng.random((batch, 32, 32, 3), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    b = {"features": (jnp.asarray(x),), "labels": (jnp.asarray(y),)}
    stepper, args_fn = _net_stepper(net, b)
    sec = _time_steps(stepper, args_fn, warmup=3, steps=steps)
    _emit("vgg16", batch / sec, "images/sec/chip",
          metric=f"vgg16_cifar_images_per_sec_{backend}")


def bench_word2vec() -> None:
    """Skip-gram NS words/sec on a synthetic zipf corpus (text8 stand-in —
    zero-egress environment, so the real text8 download is out of reach)."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    rng = np.random.default_rng(0)
    vocab, n_words, sent_len = 2000, 200_000, 25
    zipf = 1.0 / np.arange(1, vocab + 1)
    p = zipf / zipf.sum()
    words = [f"w{i}" for i in range(vocab)]
    ids = rng.choice(vocab, size=n_words, p=p)
    sents = [[words[j] for j in ids[i:i + sent_len]]
             for i in range(0, n_words, sent_len)]

    batch = 8192

    def build():
        return (Word2Vec.builder().layer_size(128).window_size(5)
                .min_word_frequency(1).negative_sample(5).batch_size(batch)
                .epochs(1).seed(1).build())

    w2v = build()
    w2v.build_vocab(sents)  # one-time host-side work, not training throughput
    # compile warmup at the true table shapes: a zero-lr flush updates
    # nothing but populates the jit cache for the timed run
    w2v._flush_sg(np.zeros(batch, np.int32), np.zeros(batch, np.int32), 0.0)
    w2v.loss_history.clear()
    t0 = time.perf_counter()
    w2v.fit(sents)
    np.asarray(w2v.word_vector("w0"))  # force pending device work to finish
    dt = time.perf_counter() - t0
    _emit("word2vec", n_words / dt, "words/sec",
          metric="word2vec_sgns_words_per_sec")


def bench_resnet_dp() -> None:
    """Allreduce-DP vs parameter-averaging steps/sec on an 8-device mesh
    (BASELINE #4: the Spark param-averaging flagship vs the ICI redesign)."""
    from deeplearning4j_tpu.util.virtual_devices import ensure_cpu_devices

    n_dev = 8
    ensure_cpu_devices(n_dev)

    from deeplearning4j_tpu.models.resnet import resnet20
    from deeplearning4j_tpu.parallel.data_parallel import (
        DataParallelTrainer,
        ParameterAveragingTrainer,
    )
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

    batch = 64
    rng = np.random.default_rng(0)
    x = rng.random((batch, 32, 32, 3), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    ds = DataSet(x, y)

    def timed_fit(trainer, n_batches):
        trainer.fit(ListDataSetIterator([ds] * 2))  # warmup/compile
        t0 = time.perf_counter()
        trainer.fit(ListDataSetIterator([ds] * n_batches))
        return n_batches / (time.perf_counter() - t0)

    mesh = make_mesh({"data": n_dev})
    net_ar = resnet20()
    net_ar.init()
    sps_allreduce = timed_fit(DataParallelTrainer(net_ar, mesh), 6)

    net_pa = resnet20()
    net_pa.init()
    sps_paramavg = timed_fit(
        ParameterAveragingTrainer(net_pa, mesh, averaging_frequency=1), 6)

    _emit("resnet_dp", sps_allreduce / sps_paramavg, "x",
          metric="resnet20_dp_allreduce_vs_paramavg_speedup",
          allreduce_steps_per_sec=round(sps_allreduce, 3),
          paramavg_steps_per_sec=round(sps_paramavg, 3))


def bench_transformer() -> None:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer import (
        transformer_flops_per_token,
        transformer_lm,
    )

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    vocab, d_model, heads, layers, d_ff = 10000, 256, 8, 6, 1024
    seq = 512 if on_tpu else 128
    batch = 16 if on_tpu else 2
    steps = 20 if on_tpu else 3
    net = transformer_lm(vocab_size=vocab, d_model=d_model, n_heads=heads,
                         n_layers=layers, d_ff=d_ff, max_length=seq,
                         dtype="bfloat16" if on_tpu else "float32")
    net.init()
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, vocab, (batch, seq)), np.int32)
    shifted = np.roll(toks, -1, axis=1)
    labels = np.eye(vocab, dtype=np.float32)[shifted]
    b = {"features": (jnp.asarray(toks),), "labels": (jnp.asarray(labels),)}
    stepper, args_fn = _net_stepper(net, b)
    sec = _time_steps(stepper, args_fn, warmup=3, steps=steps)

    tokens_per_sec = batch * seq / sec
    flops_tok = transformer_flops_per_token(vocab, d_model, layers, d_ff, seq)
    peak = _peak_flops(jax.devices()[0])
    mfu = (flops_tok * tokens_per_sec / peak) if peak else 0.0
    _emit("transformer", mfu, "MFU fraction",
          metric=f"transformer_lm_mfu_{backend}",
          tokens_per_sec=round(tokens_per_sec, 1),
          model_flops_per_token=flops_tok,
          peak_flops=peak)


MODES = {
    "lenet": bench_lenet,
    "vgg16": bench_vgg16,
    "word2vec": bench_word2vec,
    "resnet_dp": bench_resnet_dp,
    "transformer": bench_transformer,
}


def _run_all() -> int:
    """Run each mode in a subprocess (isolated jax platform init)."""
    rc = 0
    for mode in MODES:
        env = dict(os.environ)
        if mode == "resnet_dp":
            # the DP-speedup bench needs a multi-device mesh; force the
            # virtual CPU cluster regardless of how many real chips exist
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_count=8")
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), mode],
                env=env, capture_output=True, text=True, timeout=900)
        except subprocess.TimeoutExpired:
            print(json.dumps({"metric": mode, "error": "timeout"}), flush=True)
            rc = 1
            continue
        for line in out.stdout.splitlines():
            if line.startswith("{"):
                print(line, flush=True)
        if out.returncode != 0:
            sys.stderr.write(out.stderr[-2000:])
            print(json.dumps({"metric": mode, "error": f"rc={out.returncode}"}),
                  flush=True)
            rc = 1
    return rc


def main() -> int:
    if len(sys.argv) > 1:
        mode = sys.argv[1]
        if mode not in MODES:
            sys.stderr.write(f"unknown mode {mode}; one of {list(MODES)}\n")
            return 2
        MODES[mode]()
        return 0
    return _run_all()


if __name__ == "__main__":
    sys.exit(main())
